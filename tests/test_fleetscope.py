"""Fleet observatory (obs/fleetscope.py + the tenant-engine integration).

Covers:
  * device-vs-host aggregate PARITY: the in-program gate histogram,
    dispersion quantiles and top-k rank table recomputed in NumPy from
    the host-read decision table must match bit-for-bit (same
    nearest-rank formula, same masking);
  * the one-dispatch/one-sync/zero-recompile CONTRACT with fleetscope ON
    (meshprof sentinel + donation verifier — the fleet block rides the
    SAME dispatch and the SAME host_read), and the observatory toggle as
    a DECLARED cold recompile;
  * ragged-tenant pad rows (and deactivated tenants) excluded from every
    aggregate;
  * the bounded-cardinality ACCEPTANCE: fleet_* series count at N=1000
    equals the count at N=8 (O(gates + quantiles + K), never O(N)), with
    zero metric_cardinality_dropped_total;
  * the bus-metric cardinality regression (satellite): a 1000-lane bus
    stays under the 512-series cap with the drop counter at zero;
  * loadgen's decision_vetoes_total aggregation riding the DEVICE gate
    histogram (no host [N, S] scan when the observatory is on);
  * crc32-stable lane sampling + sampled decision provenance end-to-end:
    `cli why SYMBOL --lane N` resolves a vmapped lane's gate/verdict
    from the persisted JSONL, and executable decisions chain through the
    real lane executor (execution → fill);
  * alert coherence for every fleet_* series in BOTH rule engines
    (utils/alerts.py in-process + monitoring/alert_rules.yml PromQL) and
    the recording-rule / Grafana Fleet row references.
"""

import asyncio
import os

import numpy as np
import pytest

from ai_crypto_trader_tpu.config import TradingParams
from ai_crypto_trader_tpu.obs import fleetscope
from ai_crypto_trader_tpu.obs.fleetscope import (
    FleetScope,
    bin_names,
    host_aggregates,
    lane_sampled,
)
from ai_crypto_trader_tpu.obs.flightrec import GATES
from ai_crypto_trader_tpu.ops import tenant_engine
from ai_crypto_trader_tpu.ops.tenant_engine import TenantEngine
from ai_crypto_trader_tpu.utils import devprof, meshprof
from ai_crypto_trader_tpu.utils.alerts import AlertManager, default_rules
from ai_crypto_trader_tpu.utils.metrics import MetricsRegistry

REPO = os.path.join(os.path.dirname(__file__), "..")

# DELIBERATELY 9 symbols (S pads to 16): jit trace caches are shared
# across the session, and tracing the tenant program at test_tenant_
# engine.py's (N, 8) shapes FROM THIS FILE (alphabetically first) would
# rob that suite's first-trace assertions (meshprof layout cards are
# recorded at trace time) of their trace.
SYMS = [f"F{i:03d}USDC" for i in range(9)]


def _feats(eng, price, signal, strength, vol, avol, valid=None):
    S, n = eng.S, len(price)
    pad = lambda a, dt: np.asarray(list(a) + [0] * (S - n), dt)  # noqa: E731
    return {
        "price": pad(price, np.float32),
        "signal": pad(signal, np.int32),
        "strength": pad(strength, np.float32),
        "volatility": pad(vol, np.float32),
        "avg_volume": pad(avol, np.float32),
        "valid": pad(valid if valid is not None else [True] * n, bool),
    }


def _mixed_feats(eng):
    """Features that exercise several gates AND an executable entry."""
    return _feats(eng, [100.0, 50.0, 200.0, 80.0], [1, -1, 1, 0],
                  [90.0, 70.0, 40.0, 90.0], [0.015] * 4, [60_000.0] * 4)


class TestDeviceHostParity:
    def test_aggregates_match_numpy_recompute(self):
        """ACCEPTANCE: every device aggregate recomputed on host from the
        SAME decision table + state mirror must agree — histogram and
        counts exactly, quantiles/top-k to f32 tolerance."""
        with fleetscope.use(FleetScope()):
            eng = TenantEngine(SYMS, 6)      # pads to 8: 2 pad rows
            # heterogeneous lanes so quantiles/rank are non-degenerate
            eng.set_tenant(1, balance=5_000.0)
            eng.set_tenant(2, balance=20_000.0,
                           conf_threshold=0.1, min_strength=10.0)
            eng.set_tenant(4, active=False)  # deactivated, not padded
            feats = _mixed_feats(eng)
            for _ in range(3):
                out = eng.decide(feats)
            fleet = eng.last_fleet
            st = eng._state_np
            # the device aggregation slices the pow2 symbol pad back to
            # the real universe — the host recompute sees the same table
            s_real = len(eng.symbols)
            gate_full = np.full((eng.n_pad, s_real), -2, np.int8)
            gate_full[:eng.n_tenants] = out["gate"][:, :s_real]
            host = host_aggregates(
                gate=gate_full,
                pnl=(np.concatenate([out["equity"],
                                     st["balance"][eng.n_tenants:]])
                     - st["equity0"]),
                balance=st["balance"],
                max_drawdown=st["max_drawdown"],
                active=eng._params_np["active"])
            np.testing.assert_array_equal(fleet["gate_hist"],
                                          host["gate_hist"])
            assert int(fleet["decisions"]) == host["decisions"]
            assert int(fleet["executable"]) == host["executable"]
            assert int(fleet["starved"]) == host["starved"]
            assert int(fleet["active"]) == host["active"] == 5
            np.testing.assert_allclose(fleet["pnl_q"], host["pnl_q"],
                                       rtol=1e-5, atol=1e-3)
            np.testing.assert_allclose(fleet["balance_q"],
                                       host["balance_q"],
                                       rtol=1e-5, atol=1e-3)
            np.testing.assert_allclose(fleet["max_drawdown_max"],
                                       host["max_drawdown_max"],
                                       rtol=1e-5, atol=1e-3)
            # rank tables: the k active lanes agree as (lane → pnl) maps
            # (argsort tie order may differ between lax.top_k and numpy)
            k = int(fleet["active"])

            def rank_map(lanes, pnls):
                return {int(l): round(float(p), 3)
                        for l, p in zip(lanes[:k], pnls[:k])}

            assert rank_map(fleet["best_lane"], fleet["best_pnl"]) \
                == rank_map(host["best_lane"], host["best_pnl"])
            assert rank_map(fleet["worst_lane"], fleet["worst_pnl"]) \
                == rank_map(host["worst_lane"], host["worst_pnl"])

    def test_bin_names_extend_the_gate_vocabulary(self):
        names = bin_names()
        assert names[0] == "no_decision" and names[1] == "executable"
        assert names[2:] == tuple(GATES)

    def test_pad_and_deactivated_rows_excluded(self):
        """Ragged tenant counts: the pow2 pad rows (active=False by
        construction) and explicitly deactivated tenants contribute to NO
        aggregate — histogram mass, quantiles, rank table, active."""
        with fleetscope.use(FleetScope()):
            eng = TenantEngine(SYMS, 5)      # pads to 8
            eng.set_tenant(3, active=False)
            eng.decide(_mixed_feats(eng))
            fleet = eng.last_fleet
            active = 4                        # 5 − 1 deactivated
            assert int(fleet["active"]) == active
            # every counted gate cell belongs to an active row AND a
            # REAL symbol column: total histogram mass = active × S_real
            # (the pow2 symbol pad's phantom no_decision cells excluded)
            assert int(fleet["gate_hist"].sum()) \
                == active * len(eng.symbols)
            assert eng.S > len(eng.symbols)   # the pad actually exists
            k = min(int(fleet["active"]), len(fleet["best_lane"]))
            for lane in (*fleet["best_lane"][:k], *fleet["worst_lane"][:k]):
                assert int(lane) < eng.n_tenants and int(lane) != 3

    def test_rolling_pnl_and_drawdown_track_equity(self):
        """A lane that enters a position carries the fee as negative
        rolling PnL; a price drop deepens PnL AND the max-drawdown fold;
        a recovery lifts PnL but drawdown stays (monotone peak fold)."""
        with fleetscope.use(FleetScope()):
            eng = TenantEngine(SYMS, 2)
            feats = _mixed_feats(eng)
            eng.decide(feats)                 # entry on P000 at 100
            pnl_0 = eng.rolling_pnl()
            assert (pnl_0 < 0).all()          # the entry fee
            drop = dict(feats)
            drop = _feats(eng, [80.0, 50.0, 200.0, 80.0], [1, -1, 1, 0],
                          [90.0, 70.0, 40.0, 90.0], [0.015] * 4,
                          [60_000.0] * 4)
            eng.decide(drop)                  # mark-to-market at 80
            pnl_drop = eng.rolling_pnl()
            dd_drop = eng.max_drawdowns()
            assert (pnl_drop < pnl_0).all()
            assert (dd_drop > 0).all()
            recover = _feats(eng, [120.0, 50.0, 200.0, 80.0],
                             [1, -1, 1, 0], [90.0, 70.0, 40.0, 90.0],
                             [0.015] * 4, [60_000.0] * 4)
            eng.decide(recover)
            assert (eng.rolling_pnl() > pnl_drop).all()
            np.testing.assert_allclose(eng.max_drawdowns(), dd_drop,
                                       rtol=1e-5)


class TestContractWithFleetscope:
    def test_one_dispatch_one_sync_zero_recompile(self, monkeypatch):
        """The PR 12/14 contract, with the observatory ON: the fleet
        block rides the SAME dispatch and the SAME host_read — syncs
        count identically, the donation still aliases, and steady state
        never re-traces."""
        syncs = {"n": 0}
        real_read = tenant_engine.host_read

        def counting_read(tree):
            syncs["n"] += 1
            return real_read(tree)

        monkeypatch.setattr(tenant_engine, "host_read", counting_read)
        m = MetricsRegistry()
        mp = meshprof.MeshProf(metrics=m)
        with devprof.use(devprof.DevProf(metrics=m)) as dp, \
                meshprof.use(mp), fleetscope.use(FleetScope(metrics=m)):
            eng = TenantEngine(SYMS, 48)      # pads to 64
            feats = _mixed_feats(eng)
            eng.decide(feats)                 # compile + card (cold)
            assert syncs["n"] == 1
            assert eng.last_fleet is not None
            card = dp.cards["tenant_engine"]
            assert card.error is None and card.donation_ok is True
            assert dp.donation_failures == []
            eng.decide(feats)                 # steady state
            assert syncs["n"] == 2
            assert mp.recompiles.steady_total() == 0, \
                mp.recompiles.status()
            assert mp.transfers.total() == 0
            assert not eng._need_seed and eng.full_seeds == 1

    def test_observatory_toggle_is_a_declared_recompile(self):
        """Turning fleetscope on/off swaps compiled programs — declared
        cold to the sentinel, so the toggle never pages
        SteadyStateRecompile."""
        m = MetricsRegistry()
        mp = meshprof.MeshProf(metrics=m)
        with meshprof.use(mp):
            eng = TenantEngine(SYMS, 8)
            feats = _mixed_feats(eng)
            eng.decide(feats)
            eng.decide(feats)
            with fleetscope.use(FleetScope()):
                eng.decide(feats)             # ON: new program, declared
                assert eng.last_fleet is not None
            eng.decide(feats)                 # OFF again: declared too
            assert eng.last_fleet is None
            assert mp.recompiles.steady_total() == 0, \
                mp.recompiles.status()

    def test_unexplained_balance_resync_feeds_drift(self):
        """`sync_balance` divergence WITHOUT an explaining closure lands
        in the next decide's fleetscope fold (FleetBalanceDrift input);
        an expected re-anchor (venue-side closure just learned) does
        not."""
        with fleetscope.use(FleetScope()) as fs:
            eng = TenantEngine(SYMS, 2)
            feats = _mixed_feats(eng)
            eng.decide(feats)
            assert eng.sync_balance(0, 9_000.0, expected=True)
            eng.decide(feats)
            assert fs.balance_drift_max() == 0.0
            assert eng.sync_balance(1, 5_000.0)   # unexplained
            eng.decide(feats)
            assert fs.balance_drift_max() > 0.0
            assert fs.alert_state()["fleet_balance_drift"] > 0.01


class TestBoundedCardinality:
    def _series_counts(self, m):
        fams = {}
        for store in (m.counters, m.gauges, m.histograms):
            for key in store:
                base = key.partition("{")[0]
                fams[base] = fams.get(base, 0) + 1
        return fams

    def test_fleet_series_constant_in_tenant_count(self):
        """ACCEPTANCE at N=1000: the fleet_* export is O(gates +
        quantiles + K) series — the count at 1000 tenants equals the
        count at 8, and nothing hits the registry's cardinality cap."""
        counts = {}
        for n in (8, 1000):
            m = MetricsRegistry()
            with fleetscope.use(FleetScope(metrics=m)):
                eng = TenantEngine(SYMS, n)
                eng.decide(_mixed_feats(eng))
                eng.decide(_mixed_feats(eng))
            fams = self._series_counts(m)
            counts[n] = {k: v for k, v in fams.items() if "fleet_" in k}
            assert counts[n], "no fleet series exported"
            assert "crypto_trader_tpu_metric_cardinality_dropped_total" \
                not in fams
        assert counts[8] == counts[1000]
        assert sum(counts[1000].values()) < 128   # gates + quantiles + 4K

    def test_thousand_lane_bus_stays_under_cap(self):
        """Satellite regression: 1000 `trading_signals.<lane>` channels
        roll up to ONE `trading_signals.*` family series per bus gauge —
        the registry's 512-series cap is never hit and the drop counter
        stays zero."""
        from ai_crypto_trader_tpu.shell.bus import EventBus
        from ai_crypto_trader_tpu.utils.saturation import SaturationMonitor

        m = MetricsRegistry()
        bus = EventBus(metrics=m)
        for i in range(1000):
            bus.subscribe(f"trading_signals.t{i}")
        bus.subscribe("market_updates")

        async def go():
            for i in range(1000):
                await bus.publish(f"trading_signals.t{i}", {"i": i})
            await bus.publish("market_updates", {"p": 1.0})

        asyncio.run(go())
        sat = SaturationMonitor(m, tick_budget_s=1.0)
        sat.observe_bus(bus)
        sat.end_tick(0.05)
        sat.export()
        fams = self._series_counts(m)
        for fam, count in fams.items():
            assert count < 512, (fam, count)
        assert "crypto_trader_tpu_metric_cardinality_dropped_total" \
            not in fams
        # the per-lane fidelity survives where it belongs: the bus's own
        # queue view; only the metric LABEL is bounded
        assert len(bus.queue_depths()) == 1001
        assert set(sat.last_bus) == {"trading_signals.*", "market_updates"}
        assert sat.last_bus["trading_signals.*"]["channels"] == 1000

    def test_family_depth_gauge_survives_idle_lane_publish(self):
        """A backlogged lane's depth must not be overwritten by an idle
        lane's next publish on the rolled-up family gauge (last-write-
        wins would hide backpressure from the PromQL backlog alert);
        the per-tick sync re-anchors a drained family back down."""
        from ai_crypto_trader_tpu.shell.bus import EventBus

        m = MetricsRegistry()
        bus = EventBus(metrics=m)
        q0 = bus.subscribe("trading_signals.t0")
        bus.subscribe("trading_signals.t1")
        key = ('crypto_trader_tpu_bus_queue_depth'
               '{channel="trading_signals.*"}')

        async def go():
            for _ in range(5):
                await bus.publish("trading_signals.t0", {})   # depth 5
            await bus.publish("trading_signals.t1", {})       # depth 1

        asyncio.run(go())
        assert m.gauges[key] == 5                 # max-held, not 1
        while not q0.empty():
            q0.get_nowait()                       # t0 drains
        bus.sync_family_depth_gauges()
        assert m.gauges[key] == 1                 # true current max

    def test_family_depth_hold_expires_without_saturation(self):
        """With NO saturation monitor running (enable_saturation=False),
        the max-hold must expire on its TTL instead of latching a
        transient backlog's depth into the gauge forever."""
        from ai_crypto_trader_tpu.shell.bus import EventBus

        m = MetricsRegistry()
        bus = EventBus(metrics=m, warn_interval_s=30.0)
        q0 = bus.subscribe("trading_signals.t0")
        bus.subscribe("trading_signals.t1")
        key = ('crypto_trader_tpu_bus_queue_depth'
               '{channel="trading_signals.*"}')

        async def burst():
            for _ in range(5):
                await bus.publish("trading_signals.t0", {})

        asyncio.run(burst())
        while not q0.empty():
            q0.get_nowait()
        # age the hold past the TTL (time.monotonic based)
        fam = "trading_signals.*"
        held, t_held = bus._fam_depth_hold[fam]
        bus._fam_depth_hold[fam] = (held, t_held - 31.0)
        asyncio.run(bus.publish("trading_signals.t1", {}))
        assert m.gauges[key] == 1                 # recovered, not 5

    def test_host_twin_rank_tail_matches_device_inf_masking(self):
        """host_aggregates' rank tail beyond the active count reads ∓inf
        like the device's masked lax.top_k — never an inactive lane's
        stale real PnL."""
        pnl = np.array([5.0, -3.0, 99.0, 1.0])     # lane 2 deactivated
        act = np.array([True, True, False, True])
        host = host_aggregates(
            gate=np.full((4, 2), -2, np.int8), pnl=pnl,
            balance=np.full(4, 1e4), max_drawdown=np.zeros(4),
            active=act, k=4)
        assert host["best_pnl"][3] == -np.inf
        assert host["worst_pnl"][3] == np.inf
        assert 2 not in host["best_lane"][:3]
        assert 2 not in host["worst_lane"][:3]

    def test_export_clears_stale_shares_and_rank_rows(self):
        """A gate that leaves the window reads share 0 (not its frozen
        last value), and a shrunk fleet's tail rank rows read empty
        (lane −1, pnl 0) instead of the old fleet's values."""
        m = MetricsRegistry()
        fs = FleetScope(metrics=m, window=4, min_decides=1, min_vetoes=1)
        G = len(bin_names())

        def fleet(gate_idx, n_act):
            hist = np.zeros(G, np.int64)
            hist[gate_idx] = 10
            k = n_act
            return {"gate_hist": hist, "decisions": 10, "executable": 0,
                    "starved": 0, "active": n_act,
                    "pnl_q": np.zeros(3), "balance_q": np.zeros(3),
                    "max_drawdown_max": 0.0,
                    "best_pnl": np.full(k, 7.0),
                    "best_lane": np.arange(k),
                    "worst_pnl": np.full(k, -7.0),
                    "worst_lane": np.arange(k)}

        fs.observe_decide(fleet(2, 6), tenants=6)
        share_a = 'crypto_trader_tpu_fleet_gate_share{gate="%s"}' \
                  % bin_names()[2]
        assert m.gauges[share_a] > 0
        rank5 = ('crypto_trader_tpu_fleet_lane_id'
                 '{extreme="best",rank="5"}')
        assert m.gauges[rank5] == 5
        # window rolls over to a different gate, fleet shrinks to 2
        for _ in range(4):
            fs.observe_decide(fleet(3, 2), tenants=2)
        assert m.gauges[share_a] == 0.0
        assert m.gauges[rank5] == -1
        assert m.gauges['crypto_trader_tpu_fleet_lane_pnl'
                        '{extreme="best",rank="5"}'] == 0.0

    def test_channel_family_rollup_rule(self):
        from ai_crypto_trader_tpu.utils.metrics import channel_family

        assert channel_family("trading_signals.t42") == "trading_signals.*"
        assert channel_family("trading_signals") == "trading_signals"
        assert channel_family("market_updates") == "market_updates"


class TestLaneSampling:
    def test_crc32_sample_is_stable_and_rate_bounded(self):
        a = FleetScope(sample_rate=0.1)
        b = FleetScope(sample_rate=0.1)
        assert a.sample_lanes(2048) == b.sample_lanes(2048)
        assert a.sample_lanes(2048) == [i for i in range(2048)
                                        if lane_sampled(i, 0.1)]
        frac = len(a.sample_lanes(2048)) / 2048
        assert 0.05 < frac < 0.2          # ~10%, crc32-uniform-ish

    def test_sampled_lane_membership_is_prefix_stable(self):
        """Growing the fleet never changes which existing lanes are
        sampled — `cli why --lane N` stays answerable across resizes."""
        fs = FleetScope(sample_rate=0.2)
        small = set(fs.sample_lanes(100))
        fs2 = FleetScope(sample_rate=0.2)
        large = set(fs2.sample_lanes(1000))
        assert small == {i for i in large if i < 100}


class TestLoadgenIntegration:
    def _cfg(self, **kw):
        from ai_crypto_trader_tpu.testing.loadgen import LoadConfig

        base = dict(tenants=3, symbols=2, ticks=4, warmup_ticks=2,
                    window=64, min_samples=2, seed=3, mode="vmapped")
        base.update(kw)
        return LoadConfig(**base)

    def test_vetoes_ride_the_device_histogram(self, monkeypatch):
        """Satellite: with fleetscope ON the loadgen rim never scans the
        [N, S] table on host — decision_vetoes_total comes from the
        device gate histogram (TenantEngine.veto_counts poisoned to
        prove the path)."""
        from ai_crypto_trader_tpu.testing.loadgen import run_load

        def boom(self, out=None):
            raise AssertionError("host [N,S] veto scan on the "
                                 "fleetscope path")

        monkeypatch.setattr(TenantEngine, "veto_counts", boom)
        m = MetricsRegistry()
        rep = run_load(self._cfg(), metrics=m)
        assert rep["fleet"]["decides"] > 0
        gates = {k for k in m.counters if "decision_vetoes_total" in k}
        assert gates, "no veto counters exported"

    def test_device_counts_equal_host_recompute(self):
        """The device histogram's per-gate veto counts equal a NumPy
        recompute from the engine's own decision table."""
        from ai_crypto_trader_tpu.testing.loadgen import (
            SyntheticTenantTraffic)

        m = MetricsRegistry()
        traffic = SyntheticTenantTraffic(self._cfg(), metrics=m)
        with fleetscope.use(FleetScope(metrics=m)) as fs:
            async def go():
                for _ in range(4):
                    await traffic.tick(timed=False)

            asyncio.run(go())
            eng = traffic.tenant_engine
            assert fs.veto_counts(eng.last_fleet) == eng.veto_counts()

    def test_sampled_provenance_end_to_end_with_execution(self, tmp_path):
        """ACCEPTANCE: a sampled vmapped lane's decisions — vetoes AND a
        real executable that flows through its lane executor — land as
        FlightRecorder records queryable by lane, and `cli why --lane`
        renders the gate/verdict from the persisted JSONL."""
        from ai_crypto_trader_tpu.cli import main
        from ai_crypto_trader_tpu.obs.flightrec import load_decisions
        from ai_crypto_trader_tpu.testing.loadgen import run_load

        path = str(tmp_path / "fleet_decisions.jsonl")
        permissive = TradingParams(ai_confidence_threshold=0.2,
                                   min_signal_strength=10.0)
        m = MetricsRegistry()
        with fleetscope.use(FleetScope(metrics=m, sample_rate=1.0)):
            run_load(self._cfg(trading=permissive, flightrec_path=path),
                     metrics=m)
        records, stats = load_decisions(path)
        assert not stats.get("corrupt_records")
        by_lane = {r.get("lane") for r in records}
        assert by_lane >= {0, 1, 2}
        executed = [r for r in records if r.get("status") in
                    ("executed", "closed")]
        assert executed, "no sampled executable chained through its " \
                         "lane executor"
        assert executed[0]["exec"]["client_order_id"].startswith("ld")
        assert all(r.get("verdict") for r in records)
        # the operator surface resolves it (capsys-free: main prints)
        sym = executed[0]["symbol"]
        lane = executed[0]["lane"]
        main(["why", sym, "--file", path, "--lane", str(lane),
              "--last", "5"])

    def test_off_path_measures_bare_engine(self):
        """cfg.fleetscope=False: no scope is configured, no fleet block
        in the report, vetoes fall back to the host scan — the bench
        overhead probe's OFF arm."""
        from ai_crypto_trader_tpu.testing.loadgen import run_load

        m = MetricsRegistry()
        rep = run_load(self._cfg(fleetscope=False), metrics=m)
        assert "fleet" not in rep
        assert not [k for k in m.gauges if "fleet_" in k]
        assert fleetscope.active() is None


class TestFleetAlerts:
    def _scope_with_history(self, **kw):
        fs = FleetScope(min_decides=2, min_vetoes=4, **kw)
        return fs

    def _fleet(self, hist, starved=0, decisions=None, pnl=(0.0, 0.0, 0.0),
               balance=(1e4, 1e4, 1e4)):
        hist = np.asarray(hist, np.int64)
        return {"gate_hist": hist,
                "decisions": (int(hist[1:].sum())
                              if decisions is None else decisions),
                "executable": int(hist[1]), "starved": starved,
                "active": 8, "pnl_q": np.asarray(pnl, np.float64),
                "balance_q": np.asarray(balance, np.float64),
                "max_drawdown_max": 0.0,
                "best_pnl": np.zeros(3), "best_lane": np.arange(3),
                "worst_pnl": np.zeros(3), "worst_lane": np.arange(3)}

    def test_gate_dominance_and_dispersion_fire_and_resolve(self):
        fs = self._scope_with_history()
        G = len(bin_names())
        hist = np.zeros(G, np.int64)
        hist[2] = 40                       # one gate, every veto
        for _ in range(3):
            fs.observe_decide(self._fleet(hist, pnl=(-400.0, 0.0, 400.0)),
                              tenants=8)
        state = fs.alert_state()
        assert state["fleet_gate_dominance"] == 1.0
        assert state["fleet_dominant_gate"] == bin_names()[2]
        assert state["fleet_pnl_spread"] == 800.0
        mgr = AlertManager(now_fn=lambda: 0.0)
        fired = {a["name"] for a in mgr.evaluate(state)}
        assert {"FleetGateDominance", "FleetPnLDispersionHigh"} <= fired
        # a mixed window resolves dominance
        mixed = np.zeros(G, np.int64)
        mixed[2:6] = 10
        for _ in range(64):
            fs.observe_decide(self._fleet(mixed), tenants=8)
        mgr.evaluate(fs.alert_state())
        assert "FleetGateDominance" not in mgr.active
        assert "FleetPnLDispersionHigh" not in mgr.active

    def test_starvation_windowed_min_and_outage_guard(self):
        fs = self._scope_with_history()
        G = len(bin_names())
        hist = np.zeros(G, np.int64)
        hist[1] = 8
        fs.observe_decide(self._fleet(hist, starved=2), tenants=8)
        assert fs.starved_lanes() == 0     # min-sample gated
        fs.observe_decide(self._fleet(hist, starved=3), tenants=8)
        assert fs.starved_lanes() == 2     # windowed MIN
        mgr = AlertManager(now_fn=lambda: 0.0)
        assert "FleetLaneStarved" in {a["name"] for a in
                                      mgr.evaluate(fs.alert_state())}
        # a fleet-wide outage tick (zero decisions) must not count every
        # lane starved
        dead = np.zeros(G, np.int64)
        fs2 = self._scope_with_history()
        for _ in range(4):
            fs2.observe_decide(self._fleet(dead, starved=8, decisions=0),
                               tenants=8)
        assert fs2.starved_lanes() == 0

    def test_min_veto_gate_keeps_cold_fleet_silent(self):
        fs = self._scope_with_history()
        G = len(bin_names())
        hist = np.zeros(G, np.int64)
        hist[2] = 1                        # window total 2 < min_vetoes 4
        fs.observe_decide(self._fleet(hist), tenants=8)
        fs.observe_decide(self._fleet(hist), tenants=8)
        assert fs.alert_state()["fleet_gate_dominance"] == 0.0


class TestCoherence:
    def emitted_series(self):
        from test_observability import TestStackConfigCoherence

        return TestStackConfigCoherence().emitted_series()

    def test_fleet_series_emitted_and_promql_twins_resolve(self):
        """The PR 1 coherence suite extended to the fleet series: the
        four Fleet* alerts exist in monitoring/alert_rules.yml, every
        fleet_* series they and the recording/Grafana rules reference is
        one the code emits, and the in-process twins carry the same
        names."""
        import re

        import yaml

        emitted = self.emitted_series()
        new_series = {"fleet_tenants", "fleet_active_lanes",
                      "fleet_executable", "fleet_starved_lanes",
                      "fleet_gate_dominance", "fleet_pnl_spread",
                      "fleet_balance_drift_max", "fleet_gate_share",
                      "fleet_pnl_quantile", "fleet_balance_quantile",
                      "fleet_lane_pnl", "fleet_lane_id",
                      "fleet_decides_total", "fleet_decisions_total",
                      "fleet_max_drawdown"}
        missing = new_series - emitted
        assert not missing, f"fleet series not emitted: {missing}"

        fleet_alerts = {"FleetGateDominance", "FleetPnLDispersionHigh",
                        "FleetLaneStarved", "FleetBalanceDrift"}
        rules = yaml.safe_load(
            open(os.path.join(REPO, "monitoring/alert_rules.yml")))
        alert_names = {r["alert"] for g in rules["groups"]
                       for r in g["rules"] if "alert" in r}
        assert fleet_alerts <= alert_names
        for g in rules["groups"]:
            for r in g["rules"]:
                if r.get("alert") in fleet_alerts:
                    for mm in re.finditer(
                            r"crypto_trader_tpu_([a-z0-9_]+)", r["expr"]):
                        assert mm.group(1) in emitted, mm.group(1)
        assert fleet_alerts <= {r.name for r in default_rules()}
        rec = yaml.safe_load(
            open(os.path.join(REPO, "monitoring/recording_rules.yml")))
        fleet_groups = [g for g in rec["groups"]
                        if g["name"] == "crypto_trader_tpu_fleet"]
        assert fleet_groups and fleet_groups[0]["rules"]
        for r in fleet_groups[0]["rules"]:
            for mm in re.finditer(
                    r"crypto_trader_tpu_([a-z0-9_]+?)(?![a-z0-9_:])",
                    r["expr"]):
                assert mm.group(1) in emitted, (r["record"], mm.group(1))

    def test_grafana_fleet_row_queries_emitted_series(self):
        import json as json_mod
        import re

        dash = json_mod.load(open(os.path.join(
            REPO, "monitoring/grafana/provisioning/dashboards/"
                  "system_overview.json")))
        titles = [p["title"] for p in dash["panels"]]
        assert any("Fleet" in t for t in titles)
        emitted = self.emitted_series()
        fleet_panels = [p for p in dash["panels"]
                        if "fleet" in str(p.get("targets", "")).lower()]
        assert len(fleet_panels) >= 3
        for p in fleet_panels:
            for t in p["targets"]:
                for mm in re.finditer(
                        r"crypto_trader_tpu_([a-z0-9_]+?)"
                        r"(?:_bucket|_sum|_count)?[\{\[\)\s,]",
                        t["expr"] + " "):
                    assert mm.group(1) in emitted, (p["title"],
                                                    mm.group(1))

    def test_alert_state_reaches_launcher_rules(self):
        """A launcher with enable_fleetscope folds a deciding fleet's
        alert inputs into its rule evaluation (both-engines contract at
        the integration seam)."""
        from ai_crypto_trader_tpu.data.ingest import from_dict
        from ai_crypto_trader_tpu.data.synthetic import generate_ohlcv
        from ai_crypto_trader_tpu.shell.exchange import FakeExchange
        from ai_crypto_trader_tpu.shell.launcher import TradingSystem

        series = from_dict(generate_ohlcv(n=700, seed=5),
                           symbol="BTCUSDC")
        ex = FakeExchange({"BTCUSDC": series})
        system = TradingSystem(ex, ["BTCUSDC"], now_fn=lambda: 1000.0,
                               enable_fleetscope=True)
        try:
            assert fleetscope.active() is system.fleetscope
            # a vmapped engine deciding IN this process feeds the scope
            eng = TenantEngine(SYMS, 4)
            G = len(bin_names())
            hist = np.zeros(G, np.int64)
            hist[2] = 80
            for _ in range(12):
                system.fleetscope.observe_decide(
                    {"gate_hist": hist, "decisions": 80, "executable": 0,
                     "starved": 1, "active": 4,
                     "pnl_q": np.zeros(3), "balance_q": np.zeros(3),
                     "max_drawdown_max": 0.0,
                     "best_pnl": np.zeros(1), "best_lane": np.zeros(1),
                     "worst_pnl": np.zeros(1),
                     "worst_lane": np.zeros(1)}, tenants=4)
            state = system._alert_state()
            assert state["fleet_gate_dominance"] == 1.0
            assert state["fleet_starved_lanes"] == 1
            fired = {a["name"] for a in
                     system.alerts.evaluate(state)}
            assert {"FleetGateDominance", "FleetLaneStarved"} <= fired
            del eng
        finally:
            system.shutdown()
        assert fleetscope.active() is None
