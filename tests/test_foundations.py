"""Foundations: config file IO, data ingest round-trips, PRNG discipline,
LLM adapter contract, launcher wall-clock loop."""

import asyncio
import json

import numpy as np
import pytest

from ai_crypto_trader_tpu import load_config
from ai_crypto_trader_tpu.data.ingest import from_dict, load_csv, save_csv
from ai_crypto_trader_tpu.data.synthetic import generate_ohlcv
from ai_crypto_trader_tpu.prng import fold, root_key, split_tree
from ai_crypto_trader_tpu.shell.llm import LLMTrader, TechnicalPolicyBackend


class TestConfigIO:
    def test_load_from_file_with_nested_sections(self, tmp_path):
        p = tmp_path / "cfg.json"
        p.write_text(json.dumps({
            "trading": {"stop_loss_pct": 1.25, "max_positions": 3},
            "risk": {"trailing_stop": {"strategy": "atr_based"}},
            "unknown_section": {"x": 1},
        }))
        cfg = load_config(str(p))
        assert cfg.trading.stop_loss_pct == 1.25
        assert cfg.trading.max_positions == 3
        assert cfg.risk.trailing_stop.strategy == "atr_based"
        # untouched sections keep defaults
        assert cfg.trading.take_profit_pct == 4.0

    def test_int_accepted_for_float_field(self, tmp_path):
        p = tmp_path / "c.json"
        p.write_text(json.dumps({"trading": {"stop_loss_pct": 2}}))
        cfg = load_config(str(p))
        assert cfg.trading.stop_loss_pct == 2.0
        assert isinstance(cfg.trading.stop_loss_pct, float)

    def test_bool_not_accepted_as_int(self, tmp_path):
        p = tmp_path / "c.json"
        p.write_text(json.dumps({"trading": {"max_positions": True}}))
        with pytest.raises(TypeError):
            load_config(str(p))


class TestIngest:
    def test_csv_roundtrip(self, tmp_path):
        d = generate_ohlcv(n=50, seed=1)
        series = from_dict({k: v for k, v in d.items() if k != "regime"},
                           symbol="XUSDC")
        path = save_csv(series, str(tmp_path))
        loaded = load_csv(path, symbol="XUSDC")
        np.testing.assert_allclose(loaded.close, series.close, rtol=1e-5)
        np.testing.assert_array_equal(loaded.timestamp, series.timestamp)
        assert len(loaded.slice(10, 20)) == 10

    def test_klines_to_arrays(self):
        from ai_crypto_trader_tpu.data.ingest import klines_to_arrays
        rows = [[1000 + i, 1.0 + i, 2.0 + i, 0.5 + i, 1.5 + i, 10.0, 0, 0, 0,
                 0, 0, 0] for i in range(5)]
        s = klines_to_arrays(rows, symbol="ABC")
        assert len(s) == 5 and s.high[0] == 2.0 and s.timestamp[0] == 1000


class TestPRNG:
    def test_split_tree_deterministic_and_distinct(self):
        k = root_key(7)
        t1 = split_tree(k, ("a", "b", "c"))
        t2 = split_tree(root_key(7), ("a", "b", "c"))
        np.testing.assert_array_equal(np.asarray(t1["a"]), np.asarray(t2["a"]))
        assert not np.array_equal(np.asarray(t1["a"]), np.asarray(t1["b"]))

    def test_fold_per_step(self):
        k = root_key(0)
        assert not np.array_equal(np.asarray(fold(k, 1)), np.asarray(fold(k, 2)))


class TestLLMTrader:
    def test_technical_backend_contract(self):
        async def go():
            t = LLMTrader(backend=TechnicalPolicyBackend())
            out = await t.analyze_trade_opportunity({
                "symbol": "X", "rsi": 28.0, "signal": "BUY",
                "signal_strength": 88.0})
            assert out["decision"] == "BUY"
            assert 0.0 < out["confidence"] <= 1.0
            assert "model_version" in out
            assert t.should_take_trade(out)
            weak = await t.analyze_trade_opportunity({
                "symbol": "X", "rsi": 50.0, "signal": "NEUTRAL",
                "signal_strength": 10.0})
            assert not t.should_take_trade(weak)
        asyncio.run(go())

    def test_malformed_backend_output_safe(self):
        class Broken:
            def complete(self, prompt):
                return "not json at all"

        async def go():
            t = LLMTrader(backend=Broken())
            out = await t.analyze_trade_opportunity({"symbol": "X"})
            assert out["decision"] == "HOLD" and out["confidence"] == 0.0
            risk = await t.analyze_risk_setup({"available_capital": 1000.0,
                                               "volatility": 0.03})
            assert risk["position_size"] == 250.0        # 0.25 ladder
            assert risk["take_profit_pct"] == risk["stop_loss_pct"] * 2
        asyncio.run(go())

    def test_adjust_position_size_conservative(self):
        t = LLMTrader()
        out = t.adjust_position_size(
            {"position_size": 200.0, "stop_loss_pct": 1.0,
             "take_profit_pct": 5.0},
            {"position_size": 100.0, "stop_loss_pct": 2.0,
             "take_profit_pct": 4.0})
        assert out["position_size"] == 150.0
        assert out["stop_loss_pct"] == 1.0      # min of the two
        assert out["take_profit_pct"] == 4.0    # min of the two


class TestLauncherRunLoop:
    @pytest.mark.slow
    def test_run_wall_clock(self):
        from ai_crypto_trader_tpu.shell.exchange import FakeExchange
        from ai_crypto_trader_tpu.shell.launcher import TradingSystem
        from tests.test_shell import _series

        async def go():
            ex = FakeExchange({"BTCUSDC": _series(n=400)})
            ex.advance("BTCUSDC", steps=300)
            sys_ = TradingSystem(ex, ["BTCUSDC"])
            await sys_.run(duration_s=0.05, tick_interval_s=0.01)
            # loop executed at least a few ticks without error
            assert sys_.status()["channels"].get("market_updates", 0) >= 1
        asyncio.run(go())
