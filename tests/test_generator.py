"""Strategy-structure generation tests (VERDICT r3 missing #3).

The loop must generate candidate STRUCTURES (not just parameters), score
them with the real scan engine on CV folds, register improved versions,
and beat the seed on a held-out segment the search never saw — the done
criterion from the round-3 verdict, matching
`services/ai_strategy_evaluator.py:732-1360`.
"""

import asyncio
import json

import numpy as np
import pytest

from ai_crypto_trader_tpu.data import generate_ohlcv
from ai_crypto_trader_tpu.strategy.generator import (
    RULE_NAMES, LLMStructureProposer, StrategyGenerator, StrategyStructure,
    default_seed, evaluate_structures, fold_features, mutate)

# Slow tier (VERDICT r4 next#3): golden-parity / end-to-end /
# training / sharded-compile suite — deselected by the default
# run, executed via `pytest -m slow`.
pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def ohlcv():
    # sized so search folds and the holdout tail stay in the hundreds of
    # candles: the generation-loop tests compile a handful of scan shapes
    # and this file was the suite's slowest at n=6000
    return generate_ohlcv(n=4_000, seed=11)


@pytest.fixture(scope="module")
def folds(ohlcv):
    arrays = {k: np.asarray(v)[:4_000] for k, v in ohlcv.items()
              if k != "regime"}
    half = 2_000
    return [fold_features({k: v[:half] for k, v in arrays.items()}),
            fold_features({k: v[half:] for k, v in arrays.items()})]


class TestStructure:
    def test_payload_roundtrip(self):
        s = StrategyStructure(rules=(("oscillator_consensus", 1.5),
                                     ("trend_confirmation", -0.5)),
                              buy_threshold=0.25, stop_loss=3.0)
        back = StrategyStructure.from_payload(s.to_payload())
        assert back.rules == s.rules
        assert back.buy_threshold == 0.25
        assert back.stop_loss == 3.0

    def test_from_payload_validation(self):
        """Unknown rules dropped, numerics clamped, empty set rejected —
        the code-quality gate before any candidate is evaluated."""
        s = StrategyStructure.from_payload({
            "rules": {"no_such_rule": 1.0, "stoch_rsi": 99.0},
            "buy_threshold": 5.0, "stop_loss": -3.0})
        assert s.rules == (("stoch_rsi", 2.0),)       # clamped to bound
        assert s.buy_threshold == 0.9
        assert s.stop_loss == 0.5
        assert StrategyStructure.from_payload({"rules": {"bogus": 1.0}}) is None
        assert StrategyStructure.from_payload({"rules": "garbage"}) is None

    def test_list_form_rules_accepted(self):
        s = StrategyStructure.from_payload({
            "rules": [{"name": "double_rsi", "weight": 0.7}]})
        assert s.rules == (("double_rsi", 0.7),)

    def test_weight_vector_dense_lowering(self):
        s = StrategyStructure(rules=(("trend_confirmation", 1.0),))
        w = s.weight_vector()
        assert w.shape == (len(RULE_NAMES),)
        assert w[RULE_NAMES.index("trend_confirmation")] == 1.0
        assert w.sum() == 1.0                          # everything else 0


class TestEvaluation:
    def test_batch_scores_finite_and_distinct(self, folds):
        structures = [
            default_seed(),
            StrategyStructure(rules=(("divergence_detector", 1.0),),
                              buy_threshold=0.5),
            StrategyStructure(rules=(("triple_moving_average", -1.0),),
                              buy_threshold=0.1, sell_threshold=0.1),
        ]
        scores = evaluate_structures(folds, structures)
        assert scores.shape == (3,)
        assert np.isfinite(scores).any()
        # different structures must produce different trading outcomes
        finite = scores[np.isfinite(scores)]
        assert len(set(np.round(finite, 6))) > 1 or len(finite) <= 1

    def test_partitioned_eval_matches_plain(self, folds, mesh8):
        """The candidate pool sharded over the mesh data axis must score
        bit-equal to the plain vmapped program — including a pool size
        (3) the 8-device mesh pads + masks."""
        from ai_crypto_trader_tpu.parallel import MeshPartitioner

        structures = [
            default_seed(),
            StrategyStructure(rules=(("divergence_detector", 1.0),),
                              buy_threshold=0.5),
            StrategyStructure(rules=(("triple_moving_average", -1.0),),
                              buy_threshold=0.1, sell_threshold=0.1),
        ]
        plain = evaluate_structures(folds, structures)
        sharded = evaluate_structures(folds, structures,
                                      partitioner=MeshPartitioner(mesh8))
        np.testing.assert_array_equal(plain, sharded)

    def test_never_trading_structure_scores_neg_inf(self, folds):
        # direct construction skips from_payload clamping; a blend in
        # [-1, 1] can never reach a 2.0 threshold, so zero trades happen
        s = StrategyStructure(rules=(("trend_confirmation", 1.0),),
                              buy_threshold=2.0, sell_threshold=2.0)
        scores = evaluate_structures(folds, [s])
        assert scores[0] == -np.inf

    def test_mutation_changes_structure(self):
        rng = np.random.default_rng(0)
        base = default_seed()
        muts = [mutate(rng, base, 1) for _ in range(20)]
        assert any(m.rules != base.rules for m in muts)
        for m in muts:
            assert len(m.rules) >= 1
            for n, w in m.rules:
                assert n in RULE_NAMES
                assert -2.0 <= w <= 2.0


class TestLLMProposer:
    def test_parses_llm_structures(self):
        class Canned:
            def complete(self, prompt):
                assert "oscillator_consensus" in prompt   # vocabulary shown
                return json.dumps({"structures": [
                    {"rules": {"stoch_rsi": 1.2, "bogus_rule": 3.0},
                     "buy_threshold": 0.2, "stop_loss": 1.5},
                    {"rules": {}},                        # rejected: empty
                ]})

        from ai_crypto_trader_tpu.shell.llm import LLMTrader

        p = LLMStructureProposer(llm=LLMTrader(backend=Canned()))
        out = asyncio.run(p.propose(default_seed(), {"cv_sharpe": 0.1}, 1))
        assert len(out) == 1
        assert out[0].rules == (("stoch_rsi", 1.2),)
        assert out[0].name == "llm_r1_0"

    def test_backend_failure_degrades_to_empty(self):
        class Boom:
            def complete(self, prompt):
                raise RuntimeError("down")

        from ai_crypto_trader_tpu.shell.llm import LLMTrader

        p = LLMStructureProposer(llm=LLMTrader(backend=Boom()))
        out = asyncio.run(p.propose(default_seed(), {}, 1))
        assert out == []


class TestGenerationLoop:
    def test_beats_seed_on_holdout_and_registers(self, ohlcv, tmp_path):
        """The round-3 done criterion: a deliberately weak seed, real CV
        search, registered versions, holdout comparison."""
        from ai_crypto_trader_tpu.strategy.registry import ModelRegistry

        weak_seed = StrategyStructure(
            rules=(("divergence_detector", 0.2),),
            buy_threshold=0.6, sell_threshold=0.6, name="weak_seed")
        reg = ModelRegistry(path=str(tmp_path / "registry.json"))
        gen = StrategyGenerator(registry=reg, cv_folds=2, pool_size=6,
                                max_rounds=3, patience=2, seed=3)
        out = asyncio.run(gen.generate(ohlcv, seed_structure=weak_seed))

        assert out["cv_sharpe"] >= out["seed_cv_sharpe"]
        # the generated structure must beat the seed on the held-out tail
        assert out["holdout_sharpe_best"] > out["holdout_sharpe_seed"]
        # every improvement was registered with its performance
        assert len(out["versions"]) >= 2               # seed + ≥1 improvement
        best = reg.best("generated_strategy")
        assert best is not None
        assert best["performance"]["sharpe_ratio"] == pytest.approx(
            out["cv_sharpe"], abs=1e-6)
        # structure actually changed, not just numerics of the seed rule set
        assert out["structure"].to_payload()["rules"] != \
            weak_seed.to_payload()["rules"]

    def test_llm_candidates_flow_through_loop(self, ohlcv):
        """An LLM that proposes a strong known structure should have its
        proposal adopted (source name llm_r*)."""

        class ProposeStrong:
            def complete(self, prompt):
                if "structures" in prompt:
                    return json.dumps({"structures": [
                        {"rules": {"oscillator_consensus": 1.0,
                                   "trend_confirmation": 1.0,
                                   "volume_weighted_price_momentum": 0.5},
                         "buy_threshold": 0.15, "sell_threshold": 0.2,
                         "stop_loss": 2.0, "take_profit": 5.0}]})
                return "{}"

        from ai_crypto_trader_tpu.shell.llm import LLMTrader

        weak_seed = StrategyStructure(
            rules=(("divergence_detector", 0.2),),
            buy_threshold=0.6, sell_threshold=0.6)
        gen = StrategyGenerator(llm=LLMTrader(backend=ProposeStrong()),
                                cv_folds=2, pool_size=4, max_rounds=2,
                                patience=1, seed=0)
        out = asyncio.run(gen.generate(ohlcv, seed_structure=weak_seed))
        pooled = {s for h in gen.history[1:] for s in h["pool_sources"]}
        assert any(s.startswith("llm_") for s in pooled)   # proposals evaluated

    def test_report(self, ohlcv):
        gen = StrategyGenerator(cv_folds=2, pool_size=4, max_rounds=1,
                                patience=1, seed=0)
        asyncio.run(gen.generate(ohlcv))
        r = gen.report()
        assert r["rounds"] >= 1
        assert r["best_sharpe"] >= r["seed_sharpe"] or \
            np.isinf(r["seed_sharpe"])
