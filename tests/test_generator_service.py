"""GeneratorService: scheduled structure search with live hot swap
(VERDICT r4 missing#4 — the reference runs its evaluator as a
continuously-scheduled loop, `services/ai_strategy_evaluator.py:732`, and
hot-swaps winners, `services/strategy_evolution_service.py:1402-1569`)."""

import pytest
import asyncio

import numpy as np

from ai_crypto_trader_tpu.data import generate_ohlcv
from ai_crypto_trader_tpu.shell.bus import EventBus
from ai_crypto_trader_tpu.strategy.generator import (
    GeneratorService,
    StrategyStructure,
)
from ai_crypto_trader_tpu.strategy.registry import ModelRegistry

# Slow tier (VERDICT r4 next#3): golden-parity / end-to-end /
# training / sharded-compile suite — deselected by the default
# run, executed via `pytest -m slow`.
pytestmark = pytest.mark.slow


def _klines(d, n=None):
    """bus kline rows [ts_ms, o, h, l, c, vol] from a synthetic dict."""
    n = n or len(d["close"])
    ts = np.arange(n) * 60_000.0
    return [[float(t), float(o), float(h), float(lo), float(c), float(v)]
            for t, o, h, lo, c, v in zip(ts, d["open"], d["high"], d["low"],
                                         d["close"], d["volume"])]


def _weak_seed():
    # same deliberately weak seed as test_generator.py — the search beats
    # it on holdout deterministically with this data/seed
    return StrategyStructure(rules=(("divergence_detector", 0.2),),
                             buy_threshold=0.6, sell_threshold=0.6,
                             name="weak_seed")


def test_scheduled_run_adopts_and_hot_swaps(tmp_path):
    d = generate_ohlcv(n=4_000, seed=11)
    bus = EventBus()
    bus.set("historical_data_BTCUSDC_1m", _klines(d))
    clock = {"t": 0.0}
    reg = ModelRegistry(path=str(tmp_path / "reg.json"))
    # min_candles chosen so the shape bucket (3×min) lands exactly on the
    # 3_999 closed bars, reproducing test_generator.py's seeded search
    svc = GeneratorService(bus, "BTCUSDC", registry=reg, interval_s=3600.0,
                           min_candles=1_333, cv_folds=2, pool_size=6,
                           max_rounds=3, seed=3, now_fn=lambda: clock["t"],
                           current=_weak_seed())
    q = bus.subscribe("strategy_structure_update")

    out = asyncio.run(svc.run_once())
    assert out["ran"] and out["adopted"]
    version = out["version"]

    # the structure hot-swap surface
    structure = bus.get("strategy_structure")
    assert structure["version"] == version
    assert structure["rules"]                      # a real rule graph
    assert svc.current.to_payload()["rules"] == structure["rules"]
    msg = q.get_nowait()["data"]
    assert msg["version"] == version

    # the live-params hot-swap surface: the adopted exits
    live = bus.get("strategy_params")
    assert live["stop_loss"] == structure["stop_loss"]
    assert live["take_profit"] == structure["take_profit"]

    # registry: the adopted version is ACTIVE and scored
    entry = reg.entries[version]
    assert entry["status"] == "active"
    assert entry["kind"] == "generated_strategy"

    # cadence gate: an immediate second call is interval-gated
    assert asyncio.run(svc.run_once()) == {"ran": False,
                                           "reason": "interval_gate"}


def test_history_accumulates_across_bounded_windows():
    """The monitor republishes a bounded 256-candle window; the service must
    fold successive windows into its own longer buffer."""
    d = generate_ohlcv(n=600, seed=4)
    bus = EventBus()
    svc = GeneratorService(bus, "BTCUSDC", interval_s=1e18,
                           now_fn=lambda: 0.0)
    rows = _klines(d)
    for end in (256, 400, 600):                    # sliding 256-candle window
        bus.set("historical_data_BTCUSDC_1m", rows[max(0, end - 256):end])
        asyncio.run(svc.run_once())
    # the window's LAST row is the in-progress bar and is held back — an
    # early partial snapshot must never freeze into the training history
    assert len(svc._history) == 599                # no gaps, no duplicates
    assert [r[0] for r in svc._history] == [r[0] for r in rows[:599]]


def test_executor_picks_up_hot_swapped_exits():
    """The reference executor reads the current strategy at entry time
    (`hot_swap_strategy`, strategy_evolution_service.py:349-362): a bus
    strategy_params swap must change the NEXT trade's SL/TP."""
    import sys

    sys.path.insert(0, "tests")
    from test_shell import _series

    from ai_crypto_trader_tpu.shell.exchange import FakeExchange
    from ai_crypto_trader_tpu.shell.executor import TradeExecutor

    async def go():
        bus = EventBus()
        ex = FakeExchange({"BTCUSDC": _series()}, quote_balance=10_000)
        execu = TradeExecutor(bus, ex)
        bus.set("strategy_params", {"stop_loss": 3.25, "take_profit": 7.5})
        trade = await execu.handle_signal({
            "symbol": "BTCUSDC",
            "current_price": ex.get_ticker("BTCUSDC")["price"],
            "signal": "BUY", "decision": "BUY", "confidence": 0.95,
            "signal_strength": 90.0, "volatility": 0.02, "avg_volume": 1e6})
        assert trade is not None
        assert trade.stop_loss_pct == 3.25
        assert trade.take_profit_pct == 7.5

    asyncio.run(go())
