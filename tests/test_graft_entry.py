"""The driver-facing entry points must be self-defending.

Round-1 post-mortem: MULTICHIP_r01.json went red because the driver invoked
`dryrun_multichip` in a process whose jax was already pointed at the single
real TPU chip, and the run hung on the chip lock. The entry point now forces
the virtual-CPU platform itself (re-exec when jax is already initialized),
so it must succeed from an arbitrarily hostile calling environment.
"""

import pytest
import os
import subprocess
import sys

# Slow tier (VERDICT r4 next#3): golden-parity / end-to-end /
# training / sharded-compile suite — deselected by the default
# run, executed via `pytest -m slow`.
pytestmark = pytest.mark.slow


REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_dryrun_multichip_survives_hostile_env():
    """jax pre-imported with 1 CPU device, no XLA_FLAGS: the entry point
    must re-exec into a clean 2-device interpreter and finish."""
    env = {k: v for k, v in os.environ.items()
           if k not in ("PALLAS_AXON_POOL_IPS", "JAX_PLATFORMS", "XLA_FLAGS",
                        "_GRAFT_DRYRUN_REEXEC")}
    code = (
        "import jax; assert len(jax.devices()) == 1; "
        f"import sys; sys.path.insert(0, {REPO!r}); "
        "import __graft_entry__; __graft_entry__.dryrun_multichip(2)"
    )
    out = subprocess.run(
        [sys.executable, "-c", code], env=env, cwd=REPO,
        # generous: under `-m slow -n 8` on a 1-CPU box this subprocess
        # time-slices against 8 workers and 600 s was measured too tight
        capture_output=True, text=True, timeout=1800)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "dryrun_multichip(2) OK" in out.stdout


def test_force_cpu_devices_in_process_is_noop():
    """Inside the test suite (8 virtual CPU devices already up) the guard
    must accept the environment without re-exec'ing the pytest process."""
    sys.path.insert(0, REPO)
    import __graft_entry__

    assert __graft_entry__._force_cpu_devices(8) is True
