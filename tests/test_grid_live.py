"""Grid/DCA live order lifecycle tests (VERDICT r3 missing #5).

The grid service must PLACE the ladder through ExchangeInterface, reconcile
fills on tick (including partial fills), pair fills with the opposite
order, book profit, re-anchor on band escape, and run as a launcher
cadence service — all driven by the FakeExchange matching engine.
Match: `services/grid_trading_strategy.py:517-678`.
"""

import asyncio

import numpy as np
import pytest

from ai_crypto_trader_tpu.data.ingest import OHLCV
from ai_crypto_trader_tpu.shell.bus import EventBus
from ai_crypto_trader_tpu.shell.exchange import FakeExchange
from ai_crypto_trader_tpu.strategy.dca import DCAStrategy
from ai_crypto_trader_tpu.strategy.grid_live import (
    DCAService, GridTraderService)



def flat_series(n=600, price=100.0, amp=0.0, symbol="BTCUSDC"):
    """Deterministic price path: flat, or a triangle wave of ±amp."""
    t = np.arange(n)
    close = price + amp * np.sin(t / 25.0)
    high = close + 0.2
    low = close - 0.2
    return OHLCV(timestamp=t.astype(np.int64) * 60_000,
                 open=close.astype(np.float32), high=high.astype(np.float32),
                 low=low.astype(np.float32), close=close.astype(np.float32),
                 volume=np.full(n, 1e6, np.float32), symbol=symbol)


def make_service(series, bus=None, **kw):
    ex = FakeExchange({"BTCUSDC": series}, quote_balance=100_000.0,
                      fee_rate=0.0, **{k: v for k, v in kw.items()
                                       if k == "max_fill_base"})
    ex.advance("BTCUSDC", steps=520)       # enough history for auto bounds
    svc = GridTraderService(
        exchange=ex, symbol="BTCUSDC", bus=bus,
        **{k: v for k, v in kw.items() if k != "max_fill_base"})
    return ex, svc


class TestLadderPlacement:
    def test_start_places_buy_ladder_below_price(self):
        ex, svc = make_service(flat_series(amp=5.0))
        placed = svc.start()
        assert placed >= 1
        price = ex.get_ticker("BTCUSDC")["price"]
        open_orders = list(ex.open_orders.values())
        assert len(open_orders) == placed
        for o in open_orders:
            assert o["side"] == "BUY" and o["type"] == "LIMIT"
            assert o["limit_price"] < price
        # tracked mirror matches the exchange's book
        assert set(svc.orders) == set(ex.open_orders)


class TestFillReconciliation:
    def test_buy_fill_places_paired_sell(self):
        series = flat_series(n=800, amp=5.0)
        ex, svc = make_service(series)
        svc.start()

        async def go():
            out = None
            for _ in range(120):
                ex.advance("BTCUSDC")
                out = await svc.run_once()
                if out.get("buy"):
                    return out
            return out

        out = asyncio.run(go())
        assert out["buy"] >= 1
        sells = [o for o in ex.open_orders.values() if o["side"] == "SELL"]
        assert sells, "paired SELL must rest after a BUY fill"
        # the SELL price is one grid level above its buy level
        recs = [r for r in svc.orders.values() if r["side"] == "SELL"]
        for r in recs:
            assert r["price"] == pytest.approx(
                float(svc.levels[r["level_i"] + 1]))

    def test_round_trip_books_profit_and_rearms_buy(self):
        series = flat_series(n=1200, amp=6.0)
        ex, svc = make_service(series)
        bus = EventBus()
        svc.bus = bus
        notes = bus.subscribe("grid_trade_notifications")
        svc.start()

        async def go():
            for _ in range(600):
                ex.advance("BTCUSDC")
                await svc.run_once()
                if svc.total_trades >= 1:
                    return True
            return False

        assert asyncio.run(go())
        assert svc.total_profit > 0            # sell level > buy level, no fees
        assert svc.profitable_trades >= 1
        assert not notes.empty()               # notification published
        st = bus.get("grid_profit_BTCUSDC")
        assert st["total_trades"] == svc.total_trades

    def test_partial_fills_reconciled_incrementally(self):
        """A liquidity-capped exchange fills the resting BUY across several
        candles; each reconciled slice gets its paired SELL immediately."""
        series = flat_series(n=1000, amp=5.0)
        ex, svc = make_service(series, order_size=400.0, max_fill_base=1.0)
        svc.start()
        # order_size 400 at price ~95-100 → qty ≈ 4.2 → ≥4 partial fills
        async def go():
            paired = 0
            for _ in range(400):
                ex.advance("BTCUSDC")
                await svc.run_once()
                recs = [r for r in svc.orders.values()
                        if r["side"] == "BUY" and 0 < r["filled"] < r["qty"]]
                if recs:
                    paired += 1
                    # SELL quantity so far matches the filled portion
                    sell_qty = sum(r["qty"] for r in svc.orders.values()
                                   if r["side"] == "SELL")
                    buy_filled = sum(r["filled"]
                                     for r in svc.orders.values()
                                     if r["side"] == "BUY")
                    assert sell_qty == pytest.approx(buy_filled, rel=1e-6)
                if paired >= 3:
                    return True
            return False

        assert asyncio.run(go())


class TestPairingRetry:
    def test_failed_paired_placement_is_retried(self):
        """A fill whose paired order placement fails (outage) must NOT be
        orphaned: the unpaired slice is retried on later ticks."""
        series = flat_series(n=900, amp=5.0)
        ex, svc = make_service(series)
        svc.start()
        real_place = ex.place_order
        outage = {"on": False, "blocked": 0}

        def flaky(symbol, side, order_type, quantity, price=None, **kw):
            if outage["on"] and order_type == "LIMIT":
                outage["blocked"] += 1
                raise RuntimeError("exchange down")
            return real_place(symbol, side, order_type, quantity,
                              price=price, **kw)

        ex.place_order = flaky

        async def go():
            # run until a BUY fill happens while placement is down
            outage["on"] = True
            for _ in range(200):
                ex.advance("BTCUSDC")
                out = await svc.run_once()
                if out.get("buy"):
                    break
            assert outage["blocked"] >= 1
            unpaired = [r for r in svc.orders.values()
                        if r["side"] == "BUY"
                        and r["filled"] - r["paired"] > 1e-12]
            assert unpaired, "fill slice must stay marked unpaired"
            # outage ends → the next tick pairs the orphaned slice
            outage["on"] = False
            await svc.run_once()
            still = [r for r in svc.orders.values()
                     if r["side"] == "BUY"
                     and r["filled"] - r["paired"] > 1e-12]
            assert not still
            assert any(r["side"] == "SELL" for r in svc.orders.values())

        asyncio.run(go())


class TestReanchor:
    def test_band_escape_rebuilds_ladder_with_inventory_sell(self):
        """Price breaks above the band → cancel-all, new boundaries, carry
        unsold inventory as a SELL at the nearest level above."""
        n = 1200
        t = np.arange(n)
        # flat around 100 for 600 candles, then a 40% ramp
        close = np.where(t < 600, 100 + 2 * np.sin(t / 20.0),
                         100 + (t - 600) * 0.07)
        series = OHLCV(timestamp=t.astype(np.int64) * 60_000,
                       open=close.astype(np.float32),
                       high=(close + 0.2).astype(np.float32),
                       low=(close - 0.2).astype(np.float32),
                       close=close.astype(np.float32),
                       volume=np.full(n, 1e6, np.float32), symbol="BTCUSDC")
        ex = FakeExchange({"BTCUSDC": series}, quote_balance=100_000.0,
                          fee_rate=0.0)
        ex.advance("BTCUSDC", steps=520)
        bus = EventBus()
        svc = GridTraderService(exchange=ex, symbol="BTCUSDC", bus=bus,
                                reanchor_margin_pct=1.0)
        svc.start()
        old_levels = svc.levels.copy()
        old_ids = set(svc.orders)

        async def go():
            for _ in range(680):
                ex.advance("BTCUSDC")
                out = await svc.run_once()
                if out.get("reanchored"):
                    return True
            return False

        assert asyncio.run(go())
        # the ladder was rebuilt around the new range
        assert svc.levels[-1] > old_levels[-1]
        # none of the old orders survive on the exchange
        assert not (old_ids & set(ex.open_orders))
        # new ladder is resting
        assert svc.orders

    def test_escape_detection(self):
        ex, svc = make_service(flat_series(amp=5.0))
        svc.start()
        assert not svc._escaped(float(svc.levels[len(svc.levels) // 2]))
        assert svc._escaped(float(svc.levels[-1]) * 1.05)
        assert svc._escaped(float(svc.levels[0]) * 0.95)


@pytest.mark.slow
class TestLauncherIntegration:
    def test_runs_as_extra_service(self):
        """Both services ride the launcher tick with heartbeats."""
        from ai_crypto_trader_tpu.shell.launcher import TradingSystem
        from tests.test_shell import _series

        series = _series(n=700)
        ex = FakeExchange({"BTCUSDC": series}, quote_balance=100_000.0)
        ex.advance("BTCUSDC", steps=520)
        grid = GridTraderService(exchange=ex, symbol="BTCUSDC")
        dca = DCAService(exchange=ex,
                         dca=DCAStrategy(symbol="BTCUSDC", base_amount=50.0,
                                         interval_s=60.0))
        sys_ = TradingSystem(ex, ["BTCUSDC"], extra_services=[grid, dca])

        async def go():
            for _ in range(3):
                ex.advance("BTCUSDC")
                await sys_.tick()

        asyncio.run(go())
        assert "grid" in sys_.heartbeats.beats
        assert "dca" in sys_.heartbeats.beats
        assert grid._started


class TestDCAService:
    def test_purchase_cadence_and_publication(self):
        series = flat_series(n=700, amp=2.0)
        ex = FakeExchange({"BTCUSDC": series}, quote_balance=10_000.0,
                          fee_rate=0.0)
        ex.advance("BTCUSDC", steps=520)
        bus = EventBus()
        clock = {"t": 0.0}
        dca = DCAStrategy(symbol="BTCUSDC", base_amount=100.0,
                          interval_s=3600.0)
        svc = DCAService(exchange=ex, dca=dca, bus=bus,
                         now_fn=lambda: clock["t"])
        buys = bus.subscribe("dca_purchases")

        async def go():
            r1 = await svc.run_once()           # first buy immediate
            clock["t"] += 60.0
            r2 = await svc.run_once()           # gated
            clock["t"] += 3600.0
            r3 = await svc.run_once()           # second buy
            return r1, r2, r3

        r1, r2, r3 = asyncio.run(go())
        assert r1["purchased"] and not r2["purchased"] and r3["purchased"]
        assert len(dca.purchases) == 2
        assert not buys.empty()
        assert ex.get_balances()["BTC"] > 0

    def test_rebalance_executes_market_orders(self):
        series = flat_series(n=700, price=100.0, amp=0.0)
        ex = FakeExchange({"BTCUSDC": series}, quote_balance=10_000.0,
                          fee_rate=0.0)
        ex.advance("BTCUSDC", steps=520)
        # start 100% BTC; target 50/50 vs USDC → SELL BTC drift order
        ex.balances["BTC"] = 50.0
        clock = {"t": 0.0}
        svc = DCAService(
            exchange=ex, dca=DCAStrategy(symbol="BTCUSDC",
                                         interval_s=1e12),
            now_fn=lambda: clock["t"],
            rebalance_targets={"BTC": 0.5, "USDC": 0.5},
            rebalance_interval_s=0.0)
        out = asyncio.run(svc.run_once())
        assert out["rebalanced"] == 1
        b = ex.get_balances()
        total = b["USDC"] + b["BTC"] * 100.0
        assert b["BTC"] * 100.0 / total == pytest.approx(0.5, abs=0.05)
