"""Golden parity tests: JAX indicator kernels vs pandas implementations of
the `ta` library formulas used by the reference TechnicalAnalyzer
(`binance_ml_strategy.py:40-182`)."""

import numpy as np
import pandas as pd
import pytest

import jax.numpy as jnp

from ai_crypto_trader_tpu import ops


def _series(ohlcv):
    return {k: pd.Series(np.asarray(v, np.float64)) for k, v in ohlcv.items()
            if k != "regime"}


def assert_close(ours, ref, rtol=2e-4, atol=1e-3, skip=0):
    ours = np.asarray(ours, np.float64)[skip:]
    ref = np.asarray(ref, np.float64)[skip:]
    mask = ~np.isnan(ref)
    # wherever pandas is NaN (warmup / zero-range), ours must be NaN too —
    # a too-loose warmup mask emitting finite garbage is a bug, not slack.
    assert np.isnan(ours[~mask]).all(), "finite values where reference is NaN"
    np.testing.assert_allclose(ours[mask], ref[mask], rtol=rtol, atol=atol)


class TestRolling:
    def test_sma(self, ohlcv):
        s = _series(ohlcv)["close"]
        ref = s.rolling(20).mean()
        assert_close(ops.sma(jnp.asarray(ohlcv["close"]), 20), ref)

    def test_rolling_max_min(self, ohlcv):
        s = _series(ohlcv)["high"]
        assert_close(ops.rolling_max(jnp.asarray(ohlcv["high"]), 14), s.rolling(14).max())
        s = _series(ohlcv)["low"]
        assert_close(ops.rolling_min(jnp.asarray(ohlcv["low"]), 14), s.rolling(14).min())

    def test_rolling_std(self, ohlcv):
        s = _series(ohlcv)["close"]
        ref = s.rolling(20).std(ddof=0)
        assert_close(ops.rolling_std(jnp.asarray(ohlcv["close"]), 20), ref,
                     rtol=5e-3, atol=5e-2)


class TestEMAFamily:
    def test_ema(self, ohlcv):
        s = _series(ohlcv)["close"]
        for w in (12, 26):
            ref = s.ewm(span=w, adjust=False, min_periods=w).mean()
            assert_close(ops.ema(jnp.asarray(ohlcv["close"]), w), ref)

    def test_macd(self, ohlcv):
        s = _series(ohlcv)["close"]
        fast = s.ewm(span=12, adjust=False, min_periods=12).mean()
        slow = s.ewm(span=26, adjust=False, min_periods=26).mean()
        line_ref = fast - slow
        sig_ref = line_ref.ewm(span=9, adjust=False, min_periods=9).mean()
        line, sig, hist = ops.macd(jnp.asarray(ohlcv["close"]))
        assert_close(line, line_ref, atol=5e-2)
        assert_close(sig, sig_ref, atol=5e-2, skip=60)
        assert_close(hist, line_ref - sig_ref, rtol=2e-2, atol=5e-2, skip=60)

    def test_rsi(self, ohlcv):
        s = _series(ohlcv)["close"]
        diff = s.diff()
        up = diff.clip(lower=0)
        dn = -diff.clip(upper=0)
        ag = up.ewm(alpha=1 / 14, adjust=False, min_periods=14).mean()
        al = dn.ewm(alpha=1 / 14, adjust=False, min_periods=14).mean()
        ref = 100 - 100 / (1 + ag / al)
        assert_close(ops.rsi(jnp.asarray(ohlcv["close"])), ref, atol=5e-2)

    def test_atr(self, ohlcv):
        s = _series(ohlcv)
        h, l, c = s["high"], s["low"], s["close"]
        pc = c.shift(1)
        tr = pd.concat([h - l, (h - pc).abs(), (l - pc).abs()], axis=1).max(axis=1)
        tr[0] = np.nan
        ref = tr.ewm(alpha=1 / 14, adjust=False, min_periods=14).mean()
        ours = ops.atr(*(jnp.asarray(ohlcv[k]) for k in ("high", "low", "close")))
        assert_close(ours, ref, rtol=2e-3, atol=5e-1)


class TestOscillators:
    def test_stochastic(self, ohlcv):
        s = _series(ohlcv)
        hh = s["high"].rolling(14).max()
        ll = s["low"].rolling(14).min()
        k_ref = 100 * (s["close"] - ll) / (hh - ll)
        d_ref = k_ref.rolling(3).mean()
        k, d = ops.stochastic(*(jnp.asarray(ohlcv[x]) for x in ("high", "low", "close")))
        assert_close(k, k_ref, atol=5e-2)
        assert_close(d, d_ref, atol=5e-2)

    def test_williams_r(self, ohlcv):
        s = _series(ohlcv)
        hh = s["high"].rolling(14).max()
        ll = s["low"].rolling(14).min()
        ref = -100 * (hh - s["close"]) / (hh - ll)
        ours = ops.williams_r(*(jnp.asarray(ohlcv[x]) for x in ("high", "low", "close")))
        assert_close(ours, ref, atol=5e-2)

    def test_bollinger(self, ohlcv):
        s = _series(ohlcv)["close"]
        mid = s.rolling(20).mean()
        sd = s.rolling(20).std(ddof=0)
        hi, lo = mid + 2 * sd, mid - 2 * sd
        bb = ops.bollinger(jnp.asarray(ohlcv["close"]))
        assert_close(bb.mid, mid)
        assert_close(bb.high, hi, atol=2e-1)
        assert_close(bb.low, lo, atol=2e-1)
        pos_ref = (s - lo) / (hi - lo)
        assert_close(bb.position, pos_ref, rtol=5e-3, atol=2e-2)

    def test_vwap(self, ohlcv):
        s = _series(ohlcv)
        tp = (s["high"] + s["low"] + s["close"]) / 3
        ref = (tp * s["volume"]).rolling(14).sum() / s["volume"].rolling(14).sum()
        ours = ops.vwap(*(jnp.asarray(ohlcv[x]) for x in ("high", "low", "close", "volume")))
        assert_close(ours, ref, rtol=1e-3, atol=5.0)


class TestTrendVolume:
    def test_ichimoku(self, ohlcv):
        s = _series(ohlcv)
        conv = (s["high"].rolling(9).max() + s["low"].rolling(9).min()) / 2
        base = (s["high"].rolling(26).max() + s["low"].rolling(26).min()) / 2
        a_ref = (conv + base) / 2
        b_ref = (s["high"].rolling(52).max() + s["low"].rolling(52).min()) / 2
        a, b = ops.ichimoku(jnp.asarray(ohlcv["high"]), jnp.asarray(ohlcv["low"]))
        assert_close(a, a_ref, atol=5e-1)
        assert_close(b, b_ref, atol=5e-1)

    def test_obv(self, ohlcv):
        s = _series(ohlcv)
        sign = np.sign(s["close"].diff().fillna(0.0))
        ref = (sign * s["volume"]).cumsum()
        ours = ops.obv(jnp.asarray(ohlcv["close"]), jnp.asarray(ohlcv["volume"]))
        np.testing.assert_allclose(np.asarray(ours), ref.to_numpy(),
                                   rtol=1e-3, atol=2.0)

    def test_roc(self, ohlcv):
        s = _series(ohlcv)["close"]
        ref = (s - s.shift(12)) / s.shift(12) * 100
        assert_close(ops.roc(jnp.asarray(ohlcv["close"]), 12), ref, atol=5e-2)


class TestFill:
    def test_ffill_bfill(self):
        x = jnp.array([np.nan, 1.0, np.nan, 3.0, np.nan])
        np.testing.assert_allclose(np.asarray(ops.ffill(x))[1:], [1, 1, 3, 3])
        assert np.isnan(np.asarray(ops.ffill(x))[0])
        np.testing.assert_allclose(np.asarray(ops.nanfill(x)), [1, 1, 1, 3, 3])

    def test_all_nan(self):
        x = jnp.array([np.nan, np.nan])
        np.testing.assert_allclose(np.asarray(ops.nanfill(x)), [0.0, 0.0])


class TestComputeIndicators:
    def test_shapes_and_no_nans(self, ohlcv):
        arrays = {k: jnp.asarray(v) for k, v in ohlcv.items() if k != "regime"}
        out = ops.compute_indicators(arrays)
        for name in ops.indicators.INDICATOR_NAMES:
            assert out[name].shape == arrays["close"].shape, name
            assert not np.isnan(np.asarray(out[name])).any(), name

    @pytest.mark.slow
    def test_vmap_batch(self, ohlcv):
        import jax
        arrays = {k: jnp.stack([jnp.asarray(v)[:512]] * 3)
                  for k, v in ohlcv.items() if k != "regime"}
        out = jax.vmap(lambda d: ops.compute_indicators(d, fill=True))(arrays)
        assert out["rsi"].shape == (3, 512)
        np.testing.assert_allclose(out["rsi"][0], out["rsi"][2])
