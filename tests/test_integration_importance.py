"""FeatureImportanceIntegrator: importance → selection and signal gating.

Pins the consumer side of feature importance
(`services/model_integration.py:220-350`): pruned-model outcome
predictions with the reference contract, strategy-weight adjustment from
recommendations, and — the round-2 done-criterion — selection scores that
shift when the measured importance shifts.
"""

import asyncio

import numpy as np
import pytest

from ai_crypto_trader_tpu.models.trade_importance import TradeOutcomeAnalyzer
from ai_crypto_trader_tpu.strategy import FeatureImportanceIntegrator, StrategySelector


def make_trades(rng, n=200, driver="rsi"):
    """Synthetic trade outcomes where `driver` determines win/loss."""
    trades = []
    for _ in range(n):
        feats = {
            "rsi": rng.uniform(10, 90),
            "macd": rng.normal(0, 1),
            "social_sentiment": rng.uniform(0, 1),
            "social_volume": rng.uniform(0, 1e4),
            "volatility": rng.uniform(0.001, 0.05),
        }
        pnl = 1.0 if feats[driver] > np.median([10, 90]) else -1.0
        if driver == "social_sentiment":
            pnl = 1.0 if feats[driver] > 0.5 else -1.0
        trades.append({"features": feats, "pnl": pnl + rng.normal(0, 0.01)})
    return trades


MOMENTUM_STRAT = {
    "id": "momo", "archetype": "trend_following",
    "metrics": {"sharpe_ratio": 1.0, "max_drawdown_pct": 10.0},
    "feature_weights": {"momentum": 1.0},
}
SOCIAL_STRAT = {
    "id": "social", "archetype": "trend_following",
    "metrics": {"sharpe_ratio": 1.0, "max_drawdown_pct": 10.0},
    "feature_weights": {"social": 1.0},
}


class TestOutcomeContract:
    def test_no_model_neutral(self):
        out = FeatureImportanceIntegrator().predict_trade_outcome({"rsi": 50})
        assert out == {"success_probability": 0.5, "win_probability": 0.5,
                       "confidence": 0.0, "status": "no_model",
                       "prediction": "unknown"}

    def test_fitted_model_confident_on_driver(self, rng):
        az = TradeOutcomeAnalyzer(n_trees=30, n_permutation_repeats=5)
        az.fit(make_trades(rng, driver="rsi"))
        integ = FeatureImportanceIntegrator()
        integ.update_from_analyzer(az)
        hi = integ.predict_trade_outcome({"rsi": 85.0})
        lo = integ.predict_trade_outcome({"rsi": 15.0})
        assert hi["status"] == "success"
        assert hi["success_probability"] > 0.5 > lo["success_probability"]
        assert hi["confidence"] == pytest.approx(
            abs(hi["success_probability"] - 0.5) * 2)


class TestWeightAdjustment:
    def test_prioritize_and_reconsider(self, rng):
        az = TradeOutcomeAnalyzer(n_trees=30, n_permutation_repeats=5)
        az.fit(make_trades(rng, driver="rsi"))
        integ = FeatureImportanceIntegrator()
        integ.update_from_analyzer(az)
        rec = az.importances["recommendations"]
        assert "momentum" in rec["categories_to_prioritize"]
        weights = {"momentum": 0.5, "social": 0.5, "volatility": 0.5}
        out = integ.adjust_strategy_weights(weights)
        assert out["momentum"] == pytest.approx(0.6)       # ×1.2
        for cat in rec["categories_to_reconsider"]:
            if cat in weights:
                assert out[cat] == pytest.approx(0.4)      # ×0.8

    def test_no_data_identity(self):
        w = {"momentum": 0.3}
        assert FeatureImportanceIntegrator().adjust_strategy_weights(w) == w


class TestSelectionShift:
    """The done-criterion: selection flips when importance flips."""

    def winner(self, rng, driver):
        az = TradeOutcomeAnalyzer(n_trees=30, n_permutation_repeats=5)
        az.fit(make_trades(rng, driver=driver))
        integ = FeatureImportanceIntegrator()
        integ.update_from_analyzer(az)
        # feature_importance gets decisive weight; everything else is equal
        sel = StrategySelector(weights={
            "market_regime": 0.0, "historical_performance": 0.0,
            "risk_profile": 0.0, "social_sentiment": 0.0,
            "market_volatility": 0.0, "feature_importance": 1.0})
        best = sel.select(integ.annotate([MOMENTUM_STRAT, SOCIAL_STRAT]))
        return best["id"], best["factor_scores"]["feature_importance"]

    def test_momentum_importance_selects_momentum_strategy(self, rng):
        winner, align = self.winner(rng, "rsi")
        assert winner == "momo" and align > 0.5

    def test_social_importance_selects_social_strategy(self, rng):
        winner, align = self.winner(rng, "social_sentiment")
        assert winner == "social" and align > 0.5

    def test_alignment_neutral_without_declaration(self):
        integ = FeatureImportanceIntegrator()
        integ.update_from_data({"groups": {"momentum": 1.0}})
        assert integ.feature_alignment({"id": "x"}) == 0.5


class TestAnalyzerGate:
    def test_buy_downgraded_below_threshold(self, rng):
        from ai_crypto_trader_tpu.shell.analyzer import SignalAnalyzer
        from ai_crypto_trader_tpu.shell.bus import EventBus
        from ai_crypto_trader_tpu.shell.llm import LLMTrader

        az = TradeOutcomeAnalyzer(n_trees=30, n_permutation_repeats=5)
        az.fit(make_trades(rng, driver="rsi"))
        integ = FeatureImportanceIntegrator()
        integ.update_from_analyzer(az)

        class AlwaysBuy:
            async def analyze_trade_opportunity(self, ctx):
                return {"decision": "BUY", "confidence": 0.9,
                        "reasoning": "r"}

        bus = EventBus()
        analyzer = SignalAnalyzer(bus, trader=AlwaysBuy(),
                                  outcome_model=integ,
                                  min_success_probability=0.45)
        bad = asyncio.run(analyzer.handle_update(
            {"symbol": "A", "current_price": 1.0, "rsi": 15.0}))
        assert bad["decision"] == "HOLD"
        assert "outcome gate" in bad["reasoning"]
        good = asyncio.run(analyzer.handle_update(
            {"symbol": "B", "current_price": 1.0, "rsi": 85.0}))
        assert good["decision"] == "BUY"
        assert good["success_probability"] > 0.5
