"""Write-ahead journal (utils/journal.py): checksummed append-only
records, fsync batching, torn-tail-tolerant replay, snapshot+compaction —
the durable seam the reference got from Redis (SURVEY §L1, §5.3)."""

import json
import os

from ai_crypto_trader_tpu.utils.journal import WriteAheadJournal, replay


def _path(tmp_path):
    return str(tmp_path / "trades.journal")


class TestAppendReplay:
    def test_roundtrip_ordered_and_checksummed(self, tmp_path):
        p = _path(tmp_path)
        j = WriteAheadJournal(p, fsync_every=2)
        for i in range(5):
            j.append("tick", {"i": i})
        j.close()
        records, stats = replay(p)
        assert [r["data"]["i"] for r in records] == list(range(5))
        assert [r["seq"] for r in records] == [1, 2, 3, 4, 5]
        assert stats == {"total_lines": 5, "replayed": 5,
                         "corrupt_records": 0, "torn_tail": False}

    def test_missing_file_is_a_fresh_start(self, tmp_path):
        records, stats = replay(_path(tmp_path))
        assert records == [] and stats["replayed"] == 0

    def test_seq_continues_across_reopen(self, tmp_path):
        p = _path(tmp_path)
        j = WriteAheadJournal(p)
        j.append("a", {})
        j.close()
        j2 = WriteAheadJournal(p)
        assert j2.append("b", {}) == 2
        j2.close()

    def test_flush_true_is_durable_before_return(self, tmp_path):
        """The WAL property: a flush=True record survives a crash that
        loses every batched record after it."""
        p = _path(tmp_path)
        j = WriteAheadJournal(p, fsync_every=100)
        j.append("intent", {"coid": "x"}, flush=True)
        j.append("lazy", {"n": 1})
        j.append("lazy", {"n": 2})
        j.simulate_crash()                        # batched tail lost
        records, stats = replay(p)
        assert [r["kind"] for r in records] == ["intent"]
        assert not stats["torn_tail"]


class TestCorruption:
    def test_torn_tail_dropped_silently(self, tmp_path):
        p = _path(tmp_path)
        j = WriteAheadJournal(p, fsync_every=100)
        j.append("keep", {"i": 0}, flush=True)
        j.append("torn", {"i": 1})
        j.simulate_crash(torn_tail_bytes=12)      # died mid-write(2)
        records, stats = replay(p)
        assert [r["kind"] for r in records] == ["keep"]
        assert stats["torn_tail"] is True
        assert stats["corrupt_records"] == 0

    def test_reopen_after_torn_tail_truncates_then_appends_cleanly(
            self, tmp_path):
        p = _path(tmp_path)
        j = WriteAheadJournal(p, fsync_every=100)
        j.append("keep", {}, flush=True)
        j.append("torn", {})
        j.simulate_crash(torn_tail_bytes=9)
        j2 = WriteAheadJournal(p)                 # restart over torn file
        assert j2.replay_stats["torn_tail"] is True
        j2.append("after", {}, flush=True)
        j2.close()
        records, stats = replay(p)
        assert [r["kind"] for r in records] == ["keep", "after"]
        assert stats["corrupt_records"] == 0 and not stats["torn_tail"]

    def test_bitrot_mid_file_skipped_and_counted(self, tmp_path):
        p = _path(tmp_path)
        j = WriteAheadJournal(p)
        for i in range(4):
            j.append("r", {"i": i})
        j.close()
        lines = open(p, "rb").read().splitlines(keepends=True)
        lines[1] = lines[1].replace(b'"i": 1', b'"i": 9')   # flipped bits
        with open(p, "wb") as f:
            f.writelines(lines)
        records, stats = replay(p)
        assert [r["data"]["i"] for r in records] == [0, 2, 3]
        assert stats["corrupt_records"] == 1
        assert not stats["torn_tail"]

    def test_garbage_line_mid_file_skipped(self, tmp_path):
        p = _path(tmp_path)
        j = WriteAheadJournal(p)
        j.append("a", {})
        j.append("b", {})
        j.close()
        raw = open(p, "rb").read().splitlines(keepends=True)
        with open(p, "wb") as f:
            f.write(raw[0] + b"not json at all\n" + raw[1])
        records, stats = replay(p)
        assert [r["kind"] for r in records] == ["a", "b"]
        assert stats["corrupt_records"] == 1


class TestCompaction:
    def test_compact_replaces_history_with_snapshot(self, tmp_path):
        p = _path(tmp_path)
        j = WriteAheadJournal(p)
        for i in range(50):
            j.append("r", {"i": i})
        j.compact({"open": {"BTCUSDC": 1.5}})
        j.append("post", {"i": 99}, flush=True)
        j.close()
        records, stats = replay(p)
        assert [r["kind"] for r in records] == ["snapshot", "post"]
        assert records[0]["data"] == {"open": {"BTCUSDC": 1.5}}
        assert records[1]["seq"] > records[0]["seq"]   # ordering preserved
        assert stats["replayed"] == 2

    def test_compact_is_atomic_no_tmp_left_behind(self, tmp_path):
        p = _path(tmp_path)
        j = WriteAheadJournal(p)
        j.append("r", {})
        j.compact({"s": 1})
        j.close()
        assert not os.path.exists(p + ".compact")

    def test_records_json_parseable_lines(self, tmp_path):
        p = _path(tmp_path)
        j = WriteAheadJournal(p)
        j.append("k", {"x": [1, 2]}, flush=True)
        j.close()
        with open(p) as f:
            for line in f:
                rec = json.loads(line)
                assert {"seq", "t", "kind", "data", "crc"} <= set(rec)
