"""OpenAIBackend + prompts-as-config tests (VERDICT r3 missing #2).

The backend speaks chat-completions JSON mode over the same injectable
transport seam as data/fetchers.py; these tests drive it with recorded
fixtures — the request shape is asserted against the reference's call
(`services/ai_trader.py:93-104`), and the full live path (analyzer →
signal, evolver → params) runs end-to-end on canned LLM traces.
"""

import asyncio
import json

import pytest

from ai_crypto_trader_tpu.config import LLMParams
from ai_crypto_trader_tpu.data.fetchers import Response
from ai_crypto_trader_tpu.shell.llm import (
    LLMTrader, OpenAIBackend, TechnicalPolicyBackend)



def chat_fixture(content: dict | str) -> dict:
    """A recorded chat-completions reply body."""
    text = content if isinstance(content, str) else json.dumps(content)
    return {"choices": [{"message": {"role": "assistant", "content": text}}]}


class RecordedTransport:
    """Replays canned Response bodies; records every request."""

    def __init__(self, replies):
        self.replies = list(replies)
        self.requests = []

    async def __call__(self, url, payload, headers):
        self.requests.append({"url": url, "payload": payload,
                              "headers": headers})
        status, body = self.replies.pop(0)
        return Response(status, json.dumps(body))


def make_backend(replies, **kw):
    t = RecordedTransport(replies)
    return OpenAIBackend(params=LLMParams(**kw), transport=t,
                         api_key="sk-test"), t


class TestOpenAIBackend:
    def test_request_shape_matches_reference(self):
        """`ai_trader.py:93-104`: system+user messages, temperature,
        max_tokens, response_format json_object, bearer auth."""
        backend, t = make_backend(
            [(200, chat_fixture({"decision": "BUY", "confidence": 0.9}))],
            model="gpt-4o", temperature=0.7, max_tokens=2000)
        out = asyncio.run(backend.complete("hello"))
        assert json.loads(out)["decision"] == "BUY"
        req = t.requests[0]
        assert req["url"] == "https://api.openai.com/v1/chat/completions"
        assert req["headers"]["Authorization"] == "Bearer sk-test"
        p = req["payload"]
        assert p["model"] == "gpt-4o"
        assert p["temperature"] == 0.7
        assert p["max_tokens"] == 2000
        assert p["response_format"] == {"type": "json_object"}
        assert [m["role"] for m in p["messages"]] == ["system", "user"]
        assert p["messages"][1]["content"] == "hello"

    def test_base_url_override(self):
        backend, t = make_backend(
            [(200, chat_fixture({}))],
            base_url="http://localhost:8000/v1", model="local-model")
        asyncio.run(backend.complete("x"))
        assert t.requests[0]["url"] == "http://localhost:8000/v1/chat/completions"

    def test_http_error_raises(self):
        backend, _ = make_backend([(429, {"error": "rate limit"})])
        with pytest.raises(RuntimeError, match="429"):
            asyncio.run(backend.complete("x"))

    def test_missing_key_raises(self):
        backend = OpenAIBackend(
            params=LLMParams(api_key_env="_ABSENT_KEY_ENV_"),
            transport=RecordedTransport([]))
        with pytest.raises(RuntimeError, match="_ABSENT_KEY_ENV_"):
            asyncio.run(backend.complete("x"))


class TestPromptTemplates:
    def test_analysis_prompt_formats_market_data(self):
        """The explainable analysis template renders with indicator values
        and the reference's defaults for missing social/news context
        (`ai_trader.py:59-80`)."""
        backend, t = make_backend(
            [(200, chat_fixture({"decision": "HOLD", "confidence": 0.4}))])
        trader = LLMTrader(backend=backend)
        asyncio.run(trader.analyze_trade_opportunity({
            "symbol": "BTCUSDC", "current_price": 42000.5, "rsi": 31.25,
            "trend": "UPTREND", "trend_strength": 0.8}))
        prompt = t.requests[0]["payload"]["messages"][1]["content"]
        assert "BTCUSDC" in prompt
        assert "RSI 31.25" in prompt
        assert "factor_weights" in prompt            # explainable variant
        assert "No recent news available" in prompt  # reference default
        assert "MARKET_DATA:" in prompt              # machine-readable tail

    def test_non_explainable_variant(self):
        backend, t = make_backend([(200, chat_fixture({}))],
                                  explainable=False)
        trader = LLMTrader(backend=backend, params=backend.params)
        asyncio.run(trader.analyze_trade_opportunity({"symbol": "X"}))
        prompt = t.requests[0]["payload"]["messages"][1]["content"]
        assert "factor_weights" not in prompt

    def test_bad_template_degrades_to_raw_json(self):
        """`ai_trader.py:81-85`: unknown placeholder → raw-JSON context."""
        backend, t = make_backend(
            [(200, chat_fixture({}))],
            explainable_analysis_prompt="Broken {nonexistent_placeholder}")
        trader = LLMTrader(backend=backend, params=backend.params)
        asyncio.run(trader.analyze_trade_opportunity({"symbol": "ETHUSDC"}))
        prompt = t.requests[0]["payload"]["messages"][1]["content"]
        assert "Broken" not in prompt
        assert '"symbol": "ETHUSDC"' in prompt

    def test_risk_prompt(self):
        backend, t = make_backend(
            [(200, chat_fixture({"position_size": 0.2, "stop_loss_pct": 1.0,
                                 "take_profit_pct": 3.0}))])
        trader = LLMTrader(backend=backend)
        out = asyncio.run(trader.analyze_risk_setup({
            "symbol": "BTCUSDC", "available_capital": 5000.0,
            "volatility": 0.015, "current_price": 42000.0}))
        prompt = t.requests[0]["payload"]["messages"][1]["content"]
        assert "$5000.00" in prompt
        assert out["position_size"] == 0.2
        assert out["take_profit_pct"] == 3.0

    def test_market_prompt_summarizes_symbols(self):
        backend, t = make_backend(
            [(200, chat_fixture({"market_sentiment": "BULLISH",
                                 "top_opportunities": ["AUSDC"]}))])
        trader = LLMTrader(backend=backend)
        out = asyncio.run(trader.analyze_market_conditions([
            {"symbol": "AUSDC", "current_price": 1.0, "price_change_5m": 2.0},
            {"symbol": "BUSDC", "current_price": 2.0, "price_change_5m": 1.0},
        ]))
        prompt = t.requests[0]["payload"]["messages"][1]["content"]
        assert "AUSDC" in prompt and "BUSDC" in prompt
        assert out["market_sentiment"] == "BULLISH"
        assert out["breadth"] == 1.0                 # host-side floor


class TestErrorPath:
    def test_transport_error_yields_error_decision(self):
        """`ai_trader.py:169-189`: analysis failure → ERROR decision with
        confidence 0, never an exception, and it is not tradeable."""
        backend, _ = make_backend([(500, {"error": "boom"})])
        trader = LLMTrader(backend=backend)
        out = asyncio.run(trader.analyze_trade_opportunity({"symbol": "X"}))
        assert out["decision"] == "ERROR"
        assert out["confidence"] == 0.0
        assert "explanation" in out
        assert not trader.should_take_trade(out)

    def test_risk_error_falls_back_to_ladder(self):
        backend, _ = make_backend([(500, {})])
        trader = LLMTrader(backend=backend)
        out = asyncio.run(trader.analyze_risk_setup(
            {"available_capital": 1000.0, "volatility": 0.03}))
        assert out["position_size"] == 250.0

    def test_performance_metrics_roll(self):
        backend, _ = make_backend(
            [(200, chat_fixture({"decision": "BUY", "confidence": 0.8})),
             (500, {})])
        trader = LLMTrader(backend=backend)
        ok = asyncio.run(trader.analyze_trade_opportunity({"symbol": "X"}))
        bad = asyncio.run(trader.analyze_trade_opportunity({"symbol": "X"}))
        assert ok["model_performance"]["total_trades"] == 1
        assert bad["model_performance"]["total_trades"] == 2
        assert bad["model_performance"]["success_rate"] == 0.5
        assert trader.performance_metrics["failed_trades"] == 1


class TestLivePathWithRecordedTrace:
    def test_analyzer_end_to_end(self):
        """market_updates → SignalAnalyzer → OpenAI-backed gate →
        trading_signals, on a recorded LLM trace."""
        from ai_crypto_trader_tpu.shell.analyzer import SignalAnalyzer
        from ai_crypto_trader_tpu.shell.bus import EventBus

        backend, t = make_backend([(200, chat_fixture(
            {"decision": "BUY", "confidence": 0.85,
             "reasoning": "momentum + oversold bounce"}))])
        bus = EventBus()
        analyzer = SignalAnalyzer(bus=bus, trader=LLMTrader(backend=backend))
        signals = bus.subscribe("trading_signals")

        async def go():
            return await analyzer.handle_update({
                "symbol": "BTCUSDC", "current_price": 42000.0,
                "signal": "BUY", "signal_strength": 80.0, "rsi": 28.0})

        sig = asyncio.run(go())
        assert sig["decision"] == "BUY"
        assert sig["confidence"] == 0.85
        assert sig["reasoning"] == "momentum + oversold bounce"
        assert not signals.empty()
        # the prompt the fixture answered was the reference-shaped one
        assert "RSI 28.00" in t.requests[0]["payload"]["messages"][1]["content"]

    def test_evolver_llm_path(self):
        """optimize_with_llm consumes the backend through the
        await-agnostic seam (works for the async client too)."""
        from ai_crypto_trader_tpu.shell.bus import EventBus
        from ai_crypto_trader_tpu.strategy.evolution import (
            StrategyEvolver, default_params)

        backend, _ = make_backend([(200, chat_fixture(
            {"params": {"rsi_oversold": 25.0, "take_profit": 4.0}}))])
        ev = StrategyEvolver(bus=EventBus(), llm=LLMTrader(backend=backend))
        cur = default_params()
        new, detail = asyncio.run(ev.optimize_with_llm(
            {"regime": "ranging", "history_length": 5}, cur))
        assert detail["method"] == "llm"
        assert "fallback" not in detail
        assert float(new.rsi_oversold) == 25.0
        assert float(new.take_profit) == 4.0

    def test_evolver_llm_error_falls_back_to_regime_table(self):
        from ai_crypto_trader_tpu.shell.bus import EventBus
        from ai_crypto_trader_tpu.strategy.evolution import (
            StrategyEvolver, default_params)

        backend, _ = make_backend([(500, {})])
        ev = StrategyEvolver(bus=EventBus(), llm=LLMTrader(backend=backend))
        new, detail = asyncio.run(ev.optimize_with_llm(
            {"regime": "ranging", "history_length": 5}, default_params()))
        assert detail.get("fallback") == "regime_table"


class TestTechnicalBackendDispatch:
    def test_market_wide_deterministic(self):
        trader = LLMTrader(backend=TechnicalPolicyBackend())
        out = asyncio.run(trader.analyze_market_conditions([
            {"symbol": "A", "price_change_5m": 1.0},
            {"symbol": "B", "price_change_5m": 2.0},
            {"symbol": "C", "price_change_5m": -0.5},
        ]))
        assert out["market_sentiment"] == "BULLISH"
        assert out["top_opportunities"] == ["B", "A"]

    def test_risk_dispatch(self):
        trader = LLMTrader(backend=TechnicalPolicyBackend())
        out = asyncio.run(trader.analyze_risk_setup(
            {"symbol": "X", "available_capital": 1000.0, "volatility": 0.03}))
        assert out["position_size"] == 250.0
        assert out["reasoning"] == "volatility ladder"
