"""Load & capacity observatory (testing/loadgen.py + utils/saturation.py).

Covers:
  * SaturationMonitor units — duty-cycle math against the tick budget,
    window alignment for skipped stages, min-sample gating, bus
    utilization/watermarks, scatter occupancy, host-readback share;
  * the EventBus slow-subscriber/backlog warning rate limiting
    (edge-trigger + periodic summary; counters stay exact);
  * the asyncio event-loop lag probe (a blocking call becomes a
    measured lag);
  * BusBackpressure firing under forced saturation and staying silent at
    nominal load (the overload alert test), plus StageSaturated /
    EventLoopLagHigh rule coverage in BOTH engines (in-process +
    PromQL) and series↔rule coherence for every new capacity series;
  * the load harness smoke: a real tenants×symbols load point through
    stream → fused engine → tenant lanes, zero REST steady-state;
  * the ACCEPTANCE ramp: the closed-loop controller breaches the p99
    SLO at a forced load point and the breach is attributed to a NAMED
    saturated stage by the duty gauges — telemetry, not inference;
  * launcher integration: saturation gauges + /state.json `capacity`
    block from a ticking TradingSystem;
  * the slow soak ramp (pytest -m slow).
"""

import asyncio
import json
import os
import time

import numpy as np
import pytest

from ai_crypto_trader_tpu.shell.bus import EventBus
from ai_crypto_trader_tpu.utils.alerts import AlertManager
from ai_crypto_trader_tpu.utils.metrics import MetricsRegistry
from ai_crypto_trader_tpu.utils.saturation import SaturationMonitor
from ai_crypto_trader_tpu.utils.structlog import StructuredLogger

REPO = os.path.join(os.path.dirname(__file__), "..")


class TestSaturationMonitor:
    def test_duty_cycle_is_busy_over_budget(self):
        sat = SaturationMonitor(tick_budget_s=1.0, min_samples=2)
        sat.observe_stage("monitor", 0.25)
        sat.observe_stage("analyzer", 0.75)
        duty = sat.end_tick(wall_s=1.0)
        assert duty == {"monitor": 0.25, "analyzer": 0.75}

    def test_skipped_stage_records_zero_so_windows_stay_aligned(self):
        sat = SaturationMonitor(tick_budget_s=1.0, min_samples=1)
        sat.observe_stage("monitor", 0.5)
        sat.end_tick(1.0)
        sat.end_tick(1.0)                    # monitor skipped this tick
        assert sat.windowed_duty()["monitor"] == pytest.approx(0.25)

    def test_saturated_stages_min_sample_gated(self):
        sat = SaturationMonitor(tick_budget_s=1.0, min_samples=3,
                                duty_threshold=0.75)
        for _ in range(2):
            sat.observe_stage("analyzer", 0.9)
            sat.end_tick(1.0)
        assert sat.saturated_stages() == {}   # window below min_samples
        sat.observe_stage("analyzer", 0.9)
        sat.end_tick(1.0)
        assert "analyzer" in sat.saturated_stages()
        assert sat.bottleneck_stage() == "analyzer"

    def test_below_threshold_never_saturated(self):
        sat = SaturationMonitor(tick_budget_s=1.0, min_samples=1,
                                duty_threshold=0.75)
        for _ in range(8):
            sat.observe_stage("executor", 0.3)
            sat.end_tick(1.0)
        assert sat.saturated_stages() == {}
        assert sat.bottleneck_stage() == "executor"

    def test_bus_utilization_and_watermarks(self):
        async def scenario():
            bus = EventBus(max_queue=4)
            bus.subscribe("ticks")           # never drained
            for i in range(3):
                await bus.publish("ticks", i)
            return bus

        bus = asyncio.run(scenario())
        sat = SaturationMonitor(backpressure_utilization=0.5)
        sat.observe_bus(bus)
        snap = sat.last_bus["ticks"]
        assert snap["depth"] == 3 and snap["capacity"] == 4
        assert snap["utilization"] == pytest.approx(0.75)
        assert snap["high_watermark"] == 3
        assert sat.backpressured_channels() == ["ticks"]

    def test_engine_occupancy_and_host_read_share(self):
        sat = SaturationMonitor(tick_budget_s=1.0)
        sat.observe_engine({"upload_rows": 16, "scatter_capacity": 64,
                            "host_read_s": 0.05})
        sat.end_tick(wall_s=0.2)
        assert sat.scatter_occupancy() == pytest.approx(0.25)
        assert sat.host_read_share() == pytest.approx(0.25)  # 0.05 / 0.2

    def test_export_publishes_every_capacity_series(self):
        m = MetricsRegistry()
        sat = SaturationMonitor(metrics=m, tick_budget_s=1.0, min_samples=1)
        sat.observe_stage("monitor", 0.4)
        sat.observe_engine({"upload_rows": 4, "scatter_capacity": 64,
                            "host_read_s": 0.01})
        sat.observe_loop_lag(0.002)
        bus = EventBus()
        bus.subscribe("alerts")
        sat.observe_bus(bus)
        sat.end_tick(0.5)
        sat.export()
        text = m.exposition()
        for series in ('stage_duty_cycle{stage="monitor"}',
                       'saturation_samples{stage="monitor"}',
                       'stage_busy_seconds_total{stage="monitor"}',
                       'bus_queue_utilization{channel="alerts"}',
                       'bus_queue_high_watermark{channel="alerts"}',
                       "scatter_list_occupancy", "host_readback_share",
                       "event_loop_lag_seconds"):
            assert f"crypto_trader_tpu_{series}" in text, series

    def test_status_is_the_capacity_block(self):
        sat = SaturationMonitor(tick_budget_s=0.25, min_samples=1)
        sat.observe_stage("stream", 0.2)
        sat.end_tick(0.21)
        status = sat.status()
        assert status["tick_budget_s"] == 0.25
        assert status["stage_duty"]["stream"] == pytest.approx(0.8)
        assert "stream" in status["saturated_stages"]
        assert status["bottleneck_stage"] == "stream"
        json.dumps(status)                   # must be JSON-able


class TestBusWarnRateLimit:
    """Satellite: a saturated channel must not turn the structlog stream
    into its own denial of service — edge-trigger + periodic summary,
    exact counters."""

    def _flood(self, tmp_path, n=200, warn_interval_s=30.0):
        async def scenario():
            log = StructuredLogger("bus", path=str(tmp_path / "bus.jsonl"))
            bus = EventBus(max_queue=2, log=log,
                           warn_interval_s=warn_interval_s)
            bus.subscribe("ticks")           # never drained -> drops
            for i in range(n):
                await bus.publish("ticks", i)
            return bus

        bus = asyncio.run(scenario())
        rows = [json.loads(line)
                for line in open(str(tmp_path / "bus.jsonl"))]
        return bus, rows

    def test_drop_warnings_rate_limited_counters_exact(self, tmp_path):
        bus, rows = self._flood(tmp_path, n=200)
        # 200 publishes into a maxsize-2 queue: first two fill, the next
        # 198 each drop-oldest — the counter is exact
        assert bus.dropped_counts["ticks"] == 198
        warns = [r for r in rows
                 if r["msg"].startswith("slow subscriber")]
        assert len(warns) == 1, "drop warnings were not rate limited"
        assert warns[0]["dropped"] == 1
        assert warns[0]["total_dropped"] == 1
        # the suppressed count is recoverable at the next summary
        last, suppressed = bus._drop_warn["ticks"]
        assert suppressed == 197

    def test_summary_line_fires_after_interval(self, tmp_path):
        bus, rows = self._flood(tmp_path, n=50, warn_interval_s=0.0)
        # zero interval = summary every drop: all 48 drops after the
        # edge produce lines, each carrying the running total
        warns = [r for r in rows if r["msg"].startswith("slow subscriber")]
        assert len(warns) == 48
        assert warns[-1]["total_dropped"] == 48

    def test_drop_episode_end_flushes_suppressed_summary(self, tmp_path):
        """A burst that STOPS still lands its suppressed count in the
        log: the next healthy publish after the interval flushes an
        episode-ended summary (the log, not just the counters, records
        how much was lost)."""
        async def scenario():
            log = StructuredLogger("bus", path=str(tmp_path / "f.jsonl"))
            bus = EventBus(max_queue=2, log=log, warn_interval_s=0.05)
            q = bus.subscribe("ticks")
            for i in range(10):              # 8 drops: 1 warn + 7 hidden
                await bus.publish("ticks", i)
            time.sleep(0.06)                 # the episode ends
            while not q.empty():
                q.get_nowait()               # subscriber catches up
            await bus.publish("ticks", 99)   # healthy publish: flush
            return bus

        bus = asyncio.run(scenario())
        rows = [json.loads(line) for line in open(str(tmp_path / "f.jsonl"))]
        ended = [r for r in rows if "episode ended" in r["msg"]]
        assert len(ended) == 1
        assert ended[0]["suppressed_warnings"] == 7
        assert ended[0]["total_dropped"] == 8
        assert bus.dropped_counts["ticks"] == 8      # counters exact

    def test_grow_channel_backlog_warning_rate_limited(self, tmp_path):
        async def scenario():
            log = StructuredLogger("bus", path=str(tmp_path / "g.jsonl"))
            bus = EventBus(max_queue=4, log=log, warn_interval_s=1e9)
            bus.subscribe("alerts")          # "grow": unbounded
            for i in range(64):
                await bus.publish("alerts", i)
            return bus

        bus = asyncio.run(scenario())
        rows = [json.loads(line) for line in open(str(tmp_path / "g.jsonl"))]
        backlog = [r for r in rows if "backlog" in r["msg"]]
        # 64 deep on a soft limit of 4: edge at 5, then doublings only
        # (the queue kept every message — grow channels never drop)
        assert 1 <= len(backlog) <= 5
        assert bus.dropped_counts.get("alerts", 0) == 0
        assert bus.depth_watermarks["alerts"] == 64


class TestEventLoopLagProbe:
    def test_blocking_call_becomes_measured_lag(self):
        from ai_crypto_trader_tpu.utils.health import EventLoopLagProbe

        async def scenario():
            probe = EventLoopLagProbe()
            probe.sample()                   # arm
            time.sleep(0.05)                 # a blocking host call
            await asyncio.sleep(0)           # loop regains control
            return probe

        probe = asyncio.run(scenario())
        assert probe.samples == 1
        assert probe.last_lag_s >= 0.05
        assert probe.max_lag_s >= 0.05

    def test_no_loop_is_a_noop(self):
        from ai_crypto_trader_tpu.utils.health import EventLoopLagProbe

        probe = EventLoopLagProbe()
        assert probe.sample() == 0.0         # sync context: no crash
        assert probe.samples == 0


class TestCapacityAlerts:
    """Satellite: overload fires BusBackpressure, nominal stays silent —
    and every new capacity alert exists in BOTH rule engines."""

    def _state(self, bus, **extra):
        sat = SaturationMonitor(backpressure_utilization=0.75)
        sat.observe_bus(bus)
        return {**sat.alert_state(), **extra}

    def test_bus_backpressure_fires_under_forced_saturation(self):
        async def scenario():
            bus = EventBus(max_queue=4)
            bus.subscribe("market_updates")  # stuck subscriber
            for i in range(4):               # pinned AT capacity
                await bus.publish("market_updates", i)
            return bus

        bus = asyncio.run(scenario())
        mgr = AlertManager(now_fn=lambda: 0.0)
        fired = mgr.evaluate(self._state(bus))
        assert "BusBackpressure" in {a["name"] for a in fired}

    def test_bus_backpressure_silent_at_nominal_load(self):
        async def scenario():
            bus = EventBus(max_queue=64)
            q = bus.subscribe("market_updates")
            for i in range(8):               # drained consumer: shallow
                await bus.publish("market_updates", i)
                q.get_nowait()
            return bus

        bus = asyncio.run(scenario())
        mgr = AlertManager(now_fn=lambda: 0.0)
        fired = mgr.evaluate(self._state(bus))
        names = {a["name"] for a in fired}
        assert "BusBackpressure" not in names
        assert "StageSaturated" not in names
        assert "EventLoopLagHigh" not in names

    def test_stage_saturated_and_loop_lag_rules(self):
        mgr = AlertManager(now_fn=lambda: 0.0)
        fired = mgr.evaluate({"saturated_stages": ["analyzer"],
                              "event_loop_lag_s": 0.5})
        names = {a["name"] for a in fired}
        assert {"StageSaturated", "EventLoopLagHigh"} <= names
        # resolution clears them
        mgr.evaluate({"saturated_stages": [], "event_loop_lag_s": 0.0})
        assert "StageSaturated" not in mgr.active
        assert "EventLoopLagHigh" not in mgr.active

    def test_promql_twins_exist_and_reference_emitted_series(self):
        """Coherence (the PR 1 suite, extended to the capacity series):
        the three capacity alerts exist in monitoring/alert_rules.yml,
        and every capacity/saturation/loop-lag series they (and the
        recording rules) reference is one the code emits."""
        import re

        import yaml

        from test_observability import TestStackConfigCoherence

        emitted = TestStackConfigCoherence().emitted_series()
        new_series = {"stage_duty_cycle", "saturation_samples",
                      "stage_busy_seconds_total", "bus_queue_utilization",
                      "bus_queue_high_watermark", "scatter_list_occupancy",
                      "host_readback_share", "event_loop_lag_seconds"}
        missing = new_series - emitted
        assert not missing, f"capacity series not emitted: {missing}"

        rules = yaml.safe_load(
            open(os.path.join(REPO, "monitoring/alert_rules.yml")))
        alert_names = {r["alert"] for g in rules["groups"]
                       for r in g["rules"] if "alert" in r}
        assert {"StageSaturated", "BusBackpressure",
                "EventLoopLagHigh"} <= alert_names
        # every referenced crypto_trader_tpu_* series in the capacity
        # alerts resolves to an emitted one
        for g in rules["groups"]:
            for r in g["rules"]:
                if r.get("alert") in ("StageSaturated", "BusBackpressure",
                                      "EventLoopLagHigh"):
                    for m in re.finditer(
                            r"crypto_trader_tpu_([a-z0-9_]+)", r["expr"]):
                        assert m.group(1) in emitted, m.group(1)
        # in-process twins exist with the same names
        from ai_crypto_trader_tpu.utils.alerts import default_rules

        in_process = {r.name for r in default_rules()}
        assert {"StageSaturated", "BusBackpressure",
                "EventLoopLagHigh"} <= in_process
        # recording rules for the Capacity row parse and resolve too
        rec = yaml.safe_load(
            open(os.path.join(REPO, "monitoring/recording_rules.yml")))
        rec_groups = [g for g in rec["groups"]
                      if g["name"] == "crypto_trader_tpu_capacity"]
        assert rec_groups and rec_groups[0]["rules"]


def _load_config(**kw):
    from ai_crypto_trader_tpu.testing.loadgen import LoadConfig

    base = dict(tenants=2, symbols=2, ticks=6, warmup_ticks=2, window=64,
                slo_p99_ms=250.0, min_samples=2, seed=3)
    base.update(kw)
    return LoadConfig(**base)


class TestLoadHarness:
    def test_load_point_smoke_real_path_zero_rest(self):
        """One load point through the REAL path: frames → supervisor →
        fused engine → N tenant lanes.  Steady state serves from the
        stream's candle books (zero REST kline calls), every tenant lane
        analyzed every tick, and the saturation gauges exported."""
        from ai_crypto_trader_tpu.testing.loadgen import run_load

        m = MetricsRegistry()
        rep = run_load(_load_config(), metrics=m)
        assert rep["ticks"] == 6
        assert rep["lanes"] == 4
        # every tick published every symbol, every tenant analyzed it
        assert rep["published"] == 6 * 2
        assert rep["analyzed"] == 6 * 2 * 2
        assert rep["rest_kline_calls_steady"] == 0
        assert rep["p99_ms"] > 0
        assert set(rep["stage_duty"]) >= {"stream", "analyzer", "executor"}
        assert rep["bottleneck_stage"] in rep["stage_duty"]
        text = m.exposition()
        assert 'crypto_trader_tpu_stage_duty_cycle{stage="stream"}' in text
        assert "crypto_trader_tpu_scatter_list_occupancy" in text
        assert "crypto_trader_tpu_event_loop_lag_seconds" in text

    def test_tenant_lanes_are_independent(self):
        """Lane tagging: each tenant's executor processes only its own
        analyzer's signals (N lanes, not N² cross-talk)."""
        from ai_crypto_trader_tpu.testing.loadgen import (
            LoadConfig, SyntheticTenantTraffic)

        traffic = SyntheticTenantTraffic(_load_config(tenants=3))
        assert isinstance(traffic.cfg, LoadConfig)

        async def go():
            for _ in range(3):
                await traffic.tick(timed=False)

        asyncio.run(go())
        lanes = {lane.analyzer.lane for lane in traffic.lanes}
        assert len(lanes) == 3
        for lane in traffic.lanes:
            assert lane.executor.lane == lane.analyzer.lane
        # signals on the shared bus carry their lane tag
        sig = traffic.bus.get("latest_signal_" + traffic.symbols[0])
        assert sig is not None and sig.get("lane") in lanes

    def test_ramp_breach_attributed_to_named_stage(self):
        """ACCEPTANCE: the closed-loop ramp breaches the p99 SLO under a
        forced per-lane analyzer load, and the breach point is attributed
        to the analyzer stage BY THE DUTY GAUGES — the stage is named by
        telemetry (saturated_stages from the windowed duty cycle), not
        inferred from the latency number."""
        from ai_crypto_trader_tpu.testing.loadgen import ramp

        m = MetricsRegistry()
        base = _load_config(tenants=4, ticks=6, slo_p99_ms=120.0,
                            analyzer_lag_s=0.05, min_samples=2)
        out = ramp(base, metrics=m)
        assert out["breach"] is not None, \
            f"ramp never breached: {[s['p99_ms'] for s in out['steps']]}"
        # telemetry names the forced stage
        assert "analyzer" in out["saturated_stages"]
        assert out["bottleneck_stage"] == "analyzer"
        assert out["breach"]["p99_ms"] > out["slo_p99_ms"]
        # the max sustainable point (if any) is a strictly smaller load,
        # refined to within one tenant of the breach (the bisection that
        # keeps the bench gate's tolerance meaningful)
        if out["max_sustainable"] is not None:
            assert (out["max_sustainable"]["lanes"]
                    < out["breach"]["lanes"])
            assert (out["breach"]["tenants"]
                    - out["max_sustainable"]["tenants"]) == 1
        # the attribution came from the exported gauge, same value
        key = 'crypto_trader_tpu_stage_duty_cycle{stage="analyzer"}'
        assert m.gauges[key] > 0.75
        # the injected BLOCKING lag is visible to the loop-lag probe too
        breached_steps = [s for s in out["steps"] if s["breached"]]
        assert breached_steps
        assert all(s["event_loop_lag_max_s"] >= 0.05
                   for s in breached_steps)

    def test_launcher_exports_saturation_and_capacity_block(self):
        """TradingSystem wiring: a tick exports the stage duty gauges and
        the dashboard /state.json carries the `capacity` block."""
        from ai_crypto_trader_tpu.data.ingest import from_dict
        from ai_crypto_trader_tpu.data.synthetic import generate_ohlcv
        from ai_crypto_trader_tpu.shell.exchange import FakeExchange
        from ai_crypto_trader_tpu.shell.launcher import TradingSystem

        series = from_dict({k: v for k, v in
                            generate_ohlcv(n=400, seed=5).items()
                            if k != "regime"}, symbol="BTCUSDC")
        ex = FakeExchange({"BTCUSDC": series})
        clock = {"t": 1000.0}
        system = TradingSystem(ex, ["BTCUSDC"], now_fn=lambda: clock["t"])

        async def cheap_poll(*a, **kw):
            return 1

        system.monitor.poll = cheap_poll     # no engine compile needed

        async def go():
            for _ in range(3):
                clock["t"] += 60.0
                await system.tick()

        asyncio.run(go())
        assert system.saturation is not None
        duty = system.saturation.windowed_duty()
        assert {"monitor", "analyzer", "executor"} <= set(duty)
        text = system.metrics.exposition()
        assert 'crypto_trader_tpu_stage_duty_cycle{stage="monitor"}' in text
        assert "crypto_trader_tpu_event_loop_lag_seconds" in text
        assert system.loop_lag.samples > 0
        # the /state.json capacity block
        from ai_crypto_trader_tpu.shell.dashboard_server import (
            DashboardServer)

        server = DashboardServer(system, port=0).start()
        try:
            state = server.state()
            assert "capacity" in state
            assert "stage_duty" in state["capacity"]
            json.dumps(state["capacity"])
        finally:
            server.stop()

    def test_saturated_stage_reaches_launcher_alerts(self):
        """A saturating stage raises StageSaturated through the
        launcher's own rule engine (the in-process alert path)."""
        from ai_crypto_trader_tpu.data.ingest import from_dict
        from ai_crypto_trader_tpu.data.synthetic import generate_ohlcv
        from ai_crypto_trader_tpu.shell.exchange import FakeExchange
        from ai_crypto_trader_tpu.shell.launcher import TradingSystem

        series = from_dict({k: v for k, v in
                            generate_ohlcv(n=400, seed=5).items()
                            if k != "regime"}, symbol="BTCUSDC")
        ex = FakeExchange({"BTCUSDC": series})
        clock = {"t": 1000.0}
        system = TradingSystem(ex, ["BTCUSDC"], now_fn=lambda: clock["t"],
                               tick_budget_s=0.01)   # tiny budget
        system.saturation.min_samples = 2

        async def slow_poll(*a, **kw):
            time.sleep(0.02)                 # 2× the whole tick budget
            return 1

        system.monitor.poll = slow_poll

        async def go():
            for _ in range(3):
                clock["t"] += 60.0
                await system.tick()

        asyncio.run(go())
        assert "monitor" in system.saturation.saturated_stages()
        assert "StageSaturated" in system.alerts.active


class TestRampWindowIsolation:
    """Satellite regression: ramp() reuses ONE harness across steps, so a
    heavy step's latency tail / duty windows MUST be re-windowed per step
    — otherwise the bisect converges on a stale breach."""

    def test_reset_windows_clears_sliding_state(self):
        sat = SaturationMonitor(tick_budget_s=1.0, min_samples=1)
        sat.observe_stage("analyzer", 0.9)
        sat.end_tick(1.0)
        assert sat.saturated_stages()
        sat.reset_windows()
        assert sat.windowed_duty() == {}
        assert sat.saturated_stages() == {}
        assert sat.bottleneck_stage() is None
        assert sat.ticks == 0
        # cumulative busy counters survive (they are counters)
        assert sat._busy_total["analyzer"] > 0

    def test_heavy_step_tail_does_not_bleed_into_next_step(self):
        """Measure a deliberately-saturated step, then a clean one on the
        SAME harness: the clean step's p99, duty windows and loop-lag max
        must reflect only its own ticks."""
        import asyncio
        from dataclasses import replace

        from ai_crypto_trader_tpu.testing.loadgen import (
            SyntheticTenantTraffic)

        base = _load_config(tenants=2, ticks=4, min_samples=2,
                            slo_p99_ms=120.0)
        traffic = SyntheticTenantTraffic(base, points=3)
        asyncio.run(traffic.run())                      # warm step
        traffic.cfg = replace(traffic.cfg, analyzer_lag_s=0.08)
        heavy = asyncio.run(traffic.run())
        assert heavy["p99_ms"] > 120.0
        assert "analyzer" in heavy["saturated_stages"]
        assert heavy["event_loop_lag_max_s"] >= 0.08
        # the clean step on the SAME harness: fresh windows throughout
        traffic.cfg = replace(traffic.cfg, analyzer_lag_s=0.0)
        traffic.set_tenants(2)
        clean = asyncio.run(traffic.run())
        assert clean["ticks"] == 4                      # only its own ticks
        assert len(traffic.latencies_ms) == 4
        assert clean["p99_ms"] < heavy["p99_ms"] / 2, \
            "heavy step's tail bled into the next step's p99"
        assert clean["saturated_stages"] == {}, \
            "stale duty window kept the previous step's saturation"
        assert clean["event_loop_lag_max_s"] < 0.08
        # the saturation windows hold exactly this step's samples
        for stage, window in traffic.saturation._windows.items():
            assert len(window) == 4, stage

    def test_ramp_reuses_one_harness(self, monkeypatch):
        """ramp() builds ONE SyntheticTenantTraffic for the whole
        schedule (warm stream, shared compiles) and re-provisions tenants
        per step."""
        from ai_crypto_trader_tpu.testing import loadgen

        built = []
        real = loadgen.SyntheticTenantTraffic

        class Counting(real):
            def __init__(self, *a, **kw):
                built.append(1)
                super().__init__(*a, **kw)

        monkeypatch.setattr(loadgen, "SyntheticTenantTraffic", Counting)
        out = loadgen.ramp(_load_config(tenants=4, ticks=3, min_samples=2))
        assert len(built) == 1
        assert [s["tenants"] for s in out["steps"]][:3] == [1, 2, 4]


class TestVmappedMode:
    """Tenants as a batch axis through the load harness (the rim around
    ops/tenant_engine.py — decision parity itself is pinned in
    tests/test_tenant_engine.py)."""

    def test_vmapped_load_point_zero_rest_and_gauges(self):
        from ai_crypto_trader_tpu.testing.loadgen import run_load

        m = MetricsRegistry()
        rep = run_load(_load_config(mode="vmapped", tenants=5), metrics=m)
        assert rep["mode"] == "vmapped"
        assert rep["ticks"] == 6 and rep["lanes"] == 10
        assert rep["published"] == 6 * 2
        # every tenant×published-symbol decision evaluated, ONE dispatch
        assert rep["analyzed"] == 6 * 2 * 5
        assert rep["rest_kline_calls_steady"] == 0
        assert "tenant_engine" in rep["stage_duty"]
        assert rep["capacity"]["tenant_lanes"] == 10
        assert rep["capacity"]["tenant_mode"] == "vmapped"
        text = m.exposition()
        assert 'crypto_trader_tpu_tenant_lanes{mode="vmapped"} 10' in text
        # vetoes keep flowing per gate in vmapped mode (aggregated counts)
        assert 'crypto_trader_tpu_decision_vetoes_total{gate=' in text

    def test_vmapped_ramp_breach_attributed_to_engine_stage(self):
        """The vmapped twin of the objects-mode acceptance ramp: a forced
        blocking lag inside the tenant stage breaches the SLO and the
        duty gauges name tenant_engine."""
        from ai_crypto_trader_tpu.testing.loadgen import ramp

        base = _load_config(mode="vmapped", tenants=4, ticks=5,
                            slo_p99_ms=100.0, engine_lag_s=0.12,
                            min_samples=2)
        out = ramp(base)
        assert out["mode"] == "vmapped"
        assert out["breach"] is not None
        assert "tenant_engine" in out["saturated_stages"]
        assert out["bottleneck_stage"] == "tenant_engine"

    def test_object_mode_report_stamps_mode(self):
        from ai_crypto_trader_tpu.testing.loadgen import run_load

        rep = run_load(_load_config())
        assert rep["mode"] == "objects"
        assert rep["capacity"]["tenant_mode"] == "objects"


@pytest.mark.slow
class TestLoadSoak:
    def test_soak_ramp_full(self):
        """The slow soak ramp: more tenants, more symbols, more ticks —
        the ramp either finds a breach (attributed to a named stage) or
        sustains the whole schedule; either way the telemetry is
        complete at every step and the steady state stays zero-REST."""
        from ai_crypto_trader_tpu.testing.loadgen import ramp

        base = _load_config(tenants=8, symbols=4, ticks=20,
                            warmup_ticks=3, min_samples=4,
                            slo_p99_ms=5_000.0)
        out = ramp(base)
        assert len(out["steps"]) >= 1
        for step in out["steps"]:
            assert step["ticks"] == 20
            assert step["rest_kline_calls_steady"] == 0
            assert step["published"] == 20 * 4
            assert step["analyzed"] == 20 * 4 * step["tenants"]
            assert step["bottleneck_stage"] in step["stage_duty"]
            assert np.isfinite(step["p99_ms"])
        if out["breach"] is not None:
            assert out["saturated_stages"], \
                "breach without a telemetry-named saturated stage"
        else:
            assert out["max_sustainable"]["lanes"] == 8 * 4
