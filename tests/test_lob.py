"""Device-resident limit-order book (ISSUE 13): order-flow agents, book
invariants, FakeExchange parity at top-of-book, the one-dispatch sweep
behind the Partitioner seam, and the depth-capture → calibration loop.

The three contracts that guard the subsystem:

  * **Parity oracle** — a single-scenario LOB rollout must match
    FakeExchange trade-by-trade (fills, fees, final equity) when driven
    through the identical strategy decisions on the emitted
    candle/cap/spread series (the tests/test_sim.py oracle pattern),
    across calm / liquidity_hole / spread_blowout presets;
  * **Sweep contract** — ≥ 1024 scenarios × ≥ 256 steps evaluate as ONE
    dispatch with ONE host readback, zero steady-state recompiles
    (asserted through the meshprof sentinel), a `lob_sweep` devprof cost
    card, and verified donation of the schedule buffers;
  * **Calibration round-trip** — FlowParams fitted from recorded depth
    frames reproduce the source book's mean depth profile and arrival
    rates within tolerance, and drive a LOB sweep end-to-end.

Plus property tests over the stochastic flow: the book never crosses,
level sizes never go negative, fill-ledger conservation, and bitwise
same-seed determinism.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ai_crypto_trader_tpu.data.ingest import from_dict
from ai_crypto_trader_tpu.shell.exchange import FakeExchange
from ai_crypto_trader_tpu.sim import engine, lob, scenarios
from ai_crypto_trader_tpu.sim import exchange as sx
from ai_crypto_trader_tpu.utils import devprof

f32 = np.float32


def _mk_rollout(preset, seed, T=512, **kw):
    sched = scenarios.compile_schedules(preset, 1, T, seed=seed)
    strat = kw.pop("strategy", engine.default_strategy(sl_pct=1.0,
                                                       tp_pct=1.5))
    flow = kw.pop("flow", lob.flow_params())
    out = lob.rollout_lob(jax.random.PRNGKey(seed), sched, flow=flow,
                          strategy=strat, **kw)
    return out, strat


# --------------------------------------------------------------------------
# the parity oracle: LOB rollout ≡ FakeExchange at top-of-book
# --------------------------------------------------------------------------

def _oracle_run(c: dict, cap: np.ndarray, spread: np.ndarray, fee, q0, T,
                strat: engine.SimStrategy):
    """Drive FakeExchange through the EXACT decision rule of
    `engine._strategy_step` on the LOB's emitted candle series, with the
    venue-side knobs mirrored per step: the measured top-of-book cap as
    `max_fill_base`, and the measured spread via marketable LIMIT orders
    at the touch (FakeExchange has no spread of its own — a LIMIT BUY at
    the ask fills immediately at the ask, which IS top-of-book market
    execution)."""
    al_f = f32(np.asarray(strat.alpha_fast))
    al_s = f32(np.asarray(strat.alpha_slow))
    margin = f32(np.asarray(strat.entry_margin))
    sl = f32(np.asarray(strat.sl_pct))
    tp = f32(np.asarray(strat.tp_pct))
    frac = f32(np.asarray(strat.trade_frac))
    min_not = float(np.asarray(strat.min_notional))

    series = from_dict({k: c[k] for k in
                        ("open", "high", "low", "close", "volume")},
                       symbol="SIMUSDC")
    ex = FakeExchange({"SIMUSDC": series}, quote_balance=q0, fee_rate=fee)
    ema_f = ema_s = f32(0.0)
    entry = f32(0.0)
    fills, seen = [], [0]

    def drain(t):
        for fd in ex.fills[seen[0]:]:
            fills.append((t, 1 if fd["side"] == "BUY" else -1,
                          fd["quantity"], fd["price"], fd["fee"]))
        seen[0] = len(ex.fills)

    for t in range(T):
        # measured per-step venue knobs, mirrored venue-side
        ex.max_fill_base = float(cap[t])
        if t > 0:
            ex.advance()
        drain(t)
        close = c["close"][t]
        bal = ex.get_balances()
        quote, base = bal.get("USDC", 0.0), bal.get("SIM", 0.0)
        if t == 0:
            ema_f = ema_s = f32(close)
        else:
            ema_f = f32(ema_f + al_f * f32(close - ema_f))
            ema_s = f32(ema_s + al_s * f32(close - ema_s))
        flat = base * float(close) < min_not
        resting = ex.list_open_orders("SIMUSDC")
        if flat and resting:                      # post-exit sibling cleanup
            for o in resting:
                ex.cancel_order("SIMUSDC", o["order_id"])
            resting = []
        cross = ema_f > f32(ema_s * f32(1.0 + margin))
        if flat and not resting and cross and t >= engine.WARMUP:
            qty = f32(f32(frac * f32(quote)) / close)
            # market BUY at the touch: a marketable LIMIT at the ask —
            # sim/exchange books close·(1+spread/2), in f32
            ask = f32(f32(close) * f32(1.0 + f32(spread[t]) * f32(0.5)))
            ex.max_fill_base = None       # market orders are all-or-reject
            r = ex.place_order("SIMUSDC", "BUY", "LIMIT", float(qty),
                               price=float(ask))
            ex.advance("SIMUSDC", steps=0)        # match against candle t
            if ex.order_is_open("SIMUSDC", r["order_id"]):
                # an under-funded market order is GONE, not resting
                ex.cancel_order("SIMUSDC", r["order_id"])
            ex.max_fill_base = float(cap[t])
            entry = f32(close)
            drain(t)
        elif not flat and not resting:            # protective stop + TP
            sp = f32(entry * f32(1.0 - f32(sl / f32(100.0))))
            tpp = f32(entry * f32(1.0 + f32(tp / f32(100.0))))
            ex.place_order("SIMUSDC", "SELL", "STOP_LOSS", float(base),
                           stop_price=float(sp))
            ex.place_order("SIMUSDC", "SELL", "LIMIT", float(base),
                           price=float(tpp))
    bal = ex.get_balances()
    eq = bal.get("USDC", 0.0) + bal.get("SIM", 0.0) * float(c["close"][-1])
    return fills, eq, sum(fd["fee"] for fd in ex.fills)


class TestParityOracle:
    """The acceptance contract: a single-scenario LOB run reproduces
    FakeExchange trade-by-trade at top-of-book — including the presets
    that reshape the BOOK (thin liquidity, wide spread), not just the
    price path."""

    @pytest.mark.parametrize("preset,seed", [
        ("calm", 7),
        ("liquidity_hole", 9),
        ("spread_blowout", 4),
        ("flash_crash", 3),
    ])
    def test_single_scenario_matches_fake_exchange(self, preset, seed):
        T = 512
        fee, q0 = 0.001, 10_000.0
        out, strat = _mk_rollout(preset, seed, T=T, fee_rate=fee,
                                 quote_balance=q0)
        s = out["summary"]
        n = int(s["n_fills"][0])
        assert s["dropped_fills"][0] == 0
        sim_fills = out["fills"][0][:n]
        ser = out["series"]
        c1 = {k: np.asarray(v[0]) for k, v in ser["candle"].items()}

        oracle_fills, oracle_eq, oracle_fees = _oracle_run(
            c1, np.asarray(ser["cap"][0]), np.asarray(ser["spread"][0]),
            fee, q0, T, strat)

        assert n == len(oracle_fills), \
            f"{preset}: sim {n} fills vs oracle {len(oracle_fills)}"
        for srow, orow in zip(sim_fills, oracle_fills):
            t_s, _tag, side_s, qty_s, price_s, fee_s = map(float, srow)
            t_o, side_o, qty_o, price_o, fee_o = orow
            assert (t_s, side_s) == (t_o, side_o), (srow, orow)
            np.testing.assert_allclose(qty_s, qty_o, rtol=1e-4, atol=1e-9)
            np.testing.assert_allclose(price_s, price_o, rtol=1e-5)
            np.testing.assert_allclose(fee_s, fee_o, rtol=1e-3, atol=1e-6)
        np.testing.assert_allclose(float(s["fees"][0]), oracle_fees,
                                   rtol=1e-4, atol=1e-3)
        np.testing.assert_allclose(float(s["final_equity"][0]), oracle_eq,
                                   rtol=1e-4)

    def test_parity_fills_actually_happen(self):
        """Guard the oracle itself: the pinned scenarios must trade, or
        the parity proves nothing."""
        total = 0
        for preset, seed in (("calm", 7), ("liquidity_hole", 9),
                             ("spread_blowout", 4), ("flash_crash", 3)):
            out, _ = _mk_rollout(preset, seed, T=512)
            total += int(out["summary"]["n_fills"][0])
        assert total >= 12


# --------------------------------------------------------------------------
# book invariants: property tests over the stochastic flow
# --------------------------------------------------------------------------

class TestBookInvariants:
    def _books(self, preset="mixed", B=8, T=256, seed=0, flow=None):
        sched, _ = scenarios.mixed_schedules(None, B, T, seed=seed) \
            if preset == "mixed" else (
                scenarios.compile_schedules(preset, B, T, seed=seed), None)
        return lob.rollout_lob(jax.random.PRNGKey(seed), sched,
                               flow=flow, return_book=True)

    def test_book_never_crosses(self):
        out = self._books()
        ser = out["series"]
        assert (ser["best_bid"] < ser["best_ask"]).all()
        assert (ser["spread"] > 0).all()

    def test_level_sizes_never_negative(self):
        out = self._books(seed=3)
        assert float(out["series"]["bid_sz"].min()) >= 0.0
        assert float(out["series"]["ask_sz"].min()) >= 0.0

    def test_candles_well_formed(self):
        c = self._books(seed=5)["series"]["candle"]
        assert (c["high"] >= np.maximum(c["open"], c["close"]) - 1e-3).all()
        assert (c["low"] <= np.minimum(c["open"], c["close"]) + 1e-3).all()
        assert (c["low"] > 0).all() and (c["volume"] > 0).all()

    def test_fill_ledger_conservation(self):
        """Balances + fees ≡ the fill log, per scenario (the
        sim/exchange.py fill-accounting contract, inherited through the
        LOB's reuse of its matching)."""
        out = self._books(B=8, T=512, seed=2)
        s = out["summary"]
        assert (s["n_fills"] > 0).sum() >= 4, "flow barely trades"
        q0 = 10_000.0
        for b in range(8):
            n = int(s["n_fills"][b])
            log = out["fills"][b][:n].astype(np.float64)
            if n == 0:
                continue
            side, qty, price, fee = log[:, 2], log[:, 3], log[:, 4], log[:, 5]
            buys, sells = side > 0, side < 0
            cost = qty * price
            quote_expect = (q0 - (cost[buys] + fee[buys]).sum()
                            + (cost[sells] - fee[sells]).sum())
            base_expect = qty[buys].sum() - qty[sells].sum()
            np.testing.assert_allclose(s["final_quote"][b], quote_expect,
                                       rtol=1e-4, atol=5e-2)
            np.testing.assert_allclose(s["final_base"][b], base_expect,
                                       rtol=1e-3, atol=1e-5)
            np.testing.assert_allclose(s["fees"][b], fee.sum(),
                                       rtol=1e-3, atol=1e-3)

    def test_same_seed_bitwise_deterministic(self):
        a = self._books(B=4, T=128, seed=7)
        b = self._books(B=4, T=128, seed=7)
        for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y))

    def test_presets_reshape_the_microstructure(self):
        """The tentpole's point: stress drives the FLOW.  Inside its
        scheduled window a liquidity hole starves the book's arrivals and
        a spread blowout widens the quoted spread — measured on the
        emitted book channels, conditioned on the window."""
        B, T, seed = 16, 256, 1
        calm = self._books("calm", B=B, T=T, seed=seed)["series"]
        hole_sched = scenarios.compile_schedules("liquidity_hole", B, T,
                                                 seed=seed)
        hole = lob.rollout_lob(jax.random.PRNGKey(seed), hole_sched,
                               return_book=True)["series"]
        blow_sched = scenarios.compile_schedules("spread_blowout", B, T,
                                                 seed=seed)
        blow = lob.rollout_lob(jax.random.PRNGKey(seed), blow_sched,
                               return_book=True)["series"]
        in_hole = np.asarray(hole_sched.liquidity_mult) < 0.5
        assert in_hole.any()
        assert (np.asarray(hole["cap"])[in_hole].mean()
                < 0.3 * np.asarray(calm["cap"]).mean())
        in_blow = np.asarray(blow_sched.spread) > 0.0
        assert in_blow.any()
        assert (np.asarray(blow["spread"])[in_blow].mean()
                > 5.0 * np.asarray(calm["spread"]).mean())
        # calm spread is exactly the baseline grid: 2·tick·spread0
        np.testing.assert_allclose(np.asarray(calm["spread"]),
                                   2.0e-4, rtol=1e-5)


class TestQueuePosition:
    def test_gate_none_equals_all_true(self):
        """sim/exchange.match_candle with gate=None must trace to the
        exact ungated program (the parity contract's foundation)."""
        st = sx.init_state(1_000.0, K=2, L=16)
        act = sx.no_action(2)._replace(
            place=jnp.asarray([True, False]),
            side=jnp.asarray([sx.BUY, sx.BUY], jnp.int32),
            kind=jnp.asarray([sx.LIMIT, sx.LIMIT], jnp.int32),
            qty=jnp.asarray([1.0, 0.0], jnp.float32),
            limit_price=jnp.asarray([100.0, 0.0], jnp.float32))
        candle = {k: jnp.asarray(v, jnp.float32) for k, v in
                  {"open": 100.0, "high": 101.0, "low": 99.0,
                   "close": 100.0}.items()}
        z, f = jnp.asarray(0.0), jnp.asarray(0.001)
        st = sx.apply_action(st, candle, 0, act, f, z, z, z)
        a = sx.match_candle(st, candle, 1, jnp.asarray(np.inf), z, f)
        b = sx.match_candle(st, candle, 1, jnp.asarray(np.inf), z, f,
                            gate=jnp.asarray([True, True]))
        for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
        # and a False gate blocks the price-triggered LIMIT
        c = sx.match_candle(st, candle, 1, jnp.asarray(np.inf), z, f,
                            gate=jnp.asarray([False, True]))
        assert bool(jax.device_get(c.book.active)[0])
        assert not bool(jax.device_get(a.book.active)[0])

    def test_queue_priority_delays_or_reduces_fills(self):
        """With queue_frac=1 a resting TP must wait for the queue ahead
        to trade through — fills can only happen later (or not at all)
        vs the front-of-queue parity semantics."""
        sched = scenarios.compile_schedules("calm", 4, 512, seed=11)
        kw = dict(strategy=engine.default_strategy(sl_pct=1.0, tp_pct=0.3))
        front = lob.rollout_lob(jax.random.PRNGKey(1), sched,
                                flow=lob.flow_params(queue_frac=0.0), **kw)
        back = lob.rollout_lob(jax.random.PRNGKey(1), sched,
                               flow=lob.flow_params(queue_frac=1.0), **kw)
        nf_f = front["summary"]["n_fills"].sum()
        nf_b = back["summary"]["n_fills"].sum()
        assert nf_f > 0
        assert nf_b <= nf_f
        # same flow, same candles: the MARKET view is identical — only
        # the agent's queue standing differs
        np.testing.assert_array_equal(
            np.asarray(front["series"]["candle"]["close"]),
            np.asarray(back["series"]["candle"]["close"]))


# --------------------------------------------------------------------------
# the sweep contract: ≥1024 scenarios, one dispatch behind the Partitioner
# --------------------------------------------------------------------------

class TestSweepContract:
    def test_1024_scenarios_one_dispatch_zero_recompile(self, monkeypatch):
        from ai_crypto_trader_tpu.utils import meshprof
        from ai_crypto_trader_tpu.utils.metrics import MetricsRegistry

        B, T = 1024, 256
        syncs = {"n": 0}
        real_read = lob.host_read

        def counting_read(tree):
            syncs["n"] += 1
            return real_read(tree)

        monkeypatch.setattr(lob, "host_read", counting_read)
        m = MetricsRegistry()
        mp = meshprof.MeshProf(metrics=m)
        with devprof.use(devprof.DevProf(metrics=m)) as dp, \
                meshprof.use(mp):
            out = lob.lob_sweep(jax.random.PRNGKey(0), scenario="mixed",
                                num_scenarios=B, steps=T)  # compile + card
            assert syncs["n"] == 1
            assert out["stats"]["dispatches"] == 1
            assert out["stats"]["scenarios"] == B
            assert out["summary"]["final_equity"].shape == (B,)
            assert len(out["labels"]) == B
            # cost card + donation check (acceptance criteria)
            card = dp.cards["lob_sweep"]
            assert card.error is None and card.flops > 0
            assert card.donation_ok is True
            assert dp.donation_failures == []
            # the big series stayed on device — the one sync is [B]-sized
            assert out["device"]["close"].shape == (B, T)
            assert out["device"]["equity_curve"].shape == (B, T)
            # the partitioner registered the layout card
            assert mp.layouts["lob_sweep"].population == B

            out2 = lob.lob_sweep(jax.random.PRNGKey(1), scenario="mixed",
                                 num_scenarios=B, steps=T, seed=1)
            assert mp.recompiles.steady_total() == 0, \
                mp.recompiles.status()
            assert mp.recompiles.windows["lob_sweep"] == 2
            assert mp.transfers.total() == 0
            assert syncs["n"] == 2
        # different keys/schedules → different outcomes
        assert not np.array_equal(out["summary"]["final_equity"],
                                  out2["summary"]["final_equity"])

    def test_sweep_same_seed_deterministic(self):
        a = lob.lob_sweep(jax.random.PRNGKey(5), scenario="flash_crash",
                          num_scenarios=32, steps=128, seed=2)
        b = lob.lob_sweep(jax.random.PRNGKey(5), scenario="flash_crash",
                          num_scenarios=32, steps=128, seed=2)
        for k, v in a["summary"].items():
            np.testing.assert_array_equal(v, b["summary"][k], err_msg=k)

    def test_adversarial_presets_hurt_more_than_calm(self):
        kw = dict(num_scenarios=48, steps=256, seed=4,
                  strategy=engine.default_strategy(sl_pct=1.0, tp_pct=1.5))
        calm = lob.lob_sweep(jax.random.PRNGKey(9), scenario="calm", **kw)
        swan = lob.lob_sweep(jax.random.PRNGKey(9), scenario="black_swan",
                             **kw)
        assert (swan["summary"]["min_equity"].min()
                < calm["summary"]["min_equity"].min())
        assert (swan["summary"]["max_drawdown"].max()
                > calm["summary"]["max_drawdown"].max())

    def test_sweep_accepts_calibrated_flow(self):
        out = lob.lob_sweep(jax.random.PRNGKey(2), scenario="calm",
                            num_scenarios=16, steps=64,
                            flow=lob.flow_params(limit_rate=5.0,
                                                 cancel_rate=0.2),
                            levels=16)
        assert out["stats"]["levels"] == 16
        assert np.isfinite(out["summary"]["final_equity"]).all()


# --------------------------------------------------------------------------
# calibration: captured depth frames → FlowParams → LOB (the round trip)
# --------------------------------------------------------------------------

class TestCalibration:
    TRUE = dict(limit_rate=3.0, depth_decay=0.15, cancel_rate=0.10,
                market_rate=0.4, market_size=5.0, vol=0.0, drift=0.0)

    def _measure(self, flow, key, T=600):
        """Mean depth profile + net arrival rates of a flow's book, from
        its own emitted depth records — the observable the round trip
        must reproduce."""
        from ai_crypto_trader_tpu.sim import calibrate

        sched = scenarios.compile_schedules("calm", 1, T, seed=2)
        out = lob.rollout_lob(key, sched, flow=flow, return_book=True)
        recs = calibrate.records_from_lob_series(
            out["series"], tick=float(np.asarray(flow.tick)))
        arr = calibrate.frames_to_arrays(recs)
        depth = (arr["bids"][:, :, 1].mean(0)
                 + arr["asks"][:, :, 1].mean(0)) / 2.0
        db = np.diff(arr["bids"][:, :, 1], axis=0)
        da = np.diff(arr["asks"][:, :, 1], axis=0)
        inflow = (np.maximum(db, 0).mean(0) + np.maximum(da, 0).mean(0)) / 2.0
        return recs, depth, inflow

    def test_fit_recovers_flow_parameters(self):
        from ai_crypto_trader_tpu.sim import calibrate

        true = lob.flow_params(**self.TRUE)
        recs, _, _ = self._measure(true, jax.random.PRNGKey(3))
        fitted, report = calibrate.fit_flow_params(recs)
        # geometry is exact; gross rates come out of the delta regression
        np.testing.assert_allclose(float(fitted.tick), 1e-4, rtol=0.05)
        np.testing.assert_allclose(float(fitted.spread0), 1.0, rtol=0.05)
        np.testing.assert_allclose(float(fitted.mid0), 40_000.0, rtol=0.01)
        np.testing.assert_allclose(float(fitted.depth_decay), 0.15,
                                   rtol=0.25)
        np.testing.assert_allclose(float(fitted.limit_rate), 3.0, rtol=0.30)
        np.testing.assert_allclose(float(fitted.cancel_rate), 0.10,
                                   rtol=0.35)
        assert report["frames"] == 600
        # the batched-orderbook analytics rode along
        assert report["mean_impact_curve"].shape == (3,)
        assert np.isfinite(report["mean_near_pressure"])

    def test_round_trip_through_capture_journal(self, tmp_path):
        """The acceptance loop: depth frames → DepthCapture JSONL →
        load_depth_records → fit → the fitted flow's book reproduces the
        SOURCE's mean depth profile and arrival rates — and drives a
        sweep end-to-end."""
        from ai_crypto_trader_tpu.shell.exchange import load_depth_records
        from ai_crypto_trader_tpu.shell.stream import DepthCapture
        from ai_crypto_trader_tpu.sim import calibrate

        true = lob.flow_params(**self.TRUE)
        recs, depth_src, inflow_src = self._measure(true,
                                                    jax.random.PRNGKey(3))
        path = str(tmp_path / "depth.jsonl")
        dc = DepthCapture(path=path, ring_max=64)
        for r in recs:
            dc.ingest({"lastUpdateId": r["u"], "s": r["symbol"],
                       "bids": r["bids"], "asks": r["asks"]})
        dc.close()
        fitted, _ = calibrate.fit_flow_params(load_depth_records(path))

        _, depth_fit, inflow_fit = self._measure(fitted,
                                                 jax.random.PRNGKey(11))
        depth_err = np.abs(depth_fit - depth_src).mean() / depth_src.mean()
        inflow_err = (np.abs(inflow_fit - inflow_src).mean()
                      / inflow_src.mean())
        assert depth_err < 0.25, depth_err
        assert inflow_err < 0.25, inflow_err

        out = lob.lob_sweep(jax.random.PRNGKey(4), scenario="mixed",
                            num_scenarios=32, steps=64, flow=fitted)
        assert np.isfinite(out["summary"]["final_equity"]).all()

    def test_fit_needs_frames(self):
        from ai_crypto_trader_tpu.sim import calibrate

        with pytest.raises(ValueError, match="no depth frames"):
            calibrate.fit_flow_params([])

    def test_diff_records_are_not_books(self):
        """@depth diff records are per-level CHANGES, not standing books:
        both the fit and the replay seam must refuse them rather than
        silently produce garbage."""
        from ai_crypto_trader_tpu.shell.exchange import load_depth_records
        from ai_crypto_trader_tpu.sim import calibrate

        diffs = [{"symbol": "BTCUSDC", "kind": "diff", "E": i,
                  "U": i, "u": i,
                  "bids": [[100.0, 0.0]], "asks": [[100.1, 2.0]]}
                 for i in range(10)]
        assert load_depth_records(diffs) == []
        with pytest.raises(ValueError, match="no depth frames"):
            calibrate.fit_flow_params(diffs)

    def test_explicit_symbol_miss_raises(self):
        from ai_crypto_trader_tpu.sim import calibrate

        recs = [{"symbol": "BTCUSDC", "kind": "snapshot", "E": 0, "u": 0,
                 "bids": [[100.0 - i, 1.0] for i in range(4)],
                 "asks": [[101.0 + i, 1.0] for i in range(4)]}] * 3
        with pytest.raises(ValueError, match="ETHUSDC"):
            calibrate.fit_flow_params(recs, symbol="ETHUSDC")


# --------------------------------------------------------------------------
# satellites: FakeExchange replay seam, batched ops/orderbook, workloads
# --------------------------------------------------------------------------

class TestDepthReplaySeam:
    def _records(self, n=5, symbol="BTCUSDC"):
        return [{"symbol": symbol, "kind": "snapshot", "E": i, "u": i,
                 "bids": [[100.0 - 0.1 * j, 1.0 + i + j] for j in range(8)],
                 "asks": [[100.1 + 0.1 * j, 2.0 + i + j] for j in range(8)]}
                for i in range(n)]

    def _exchange(self, records):
        from ai_crypto_trader_tpu.data.ingest import from_dict
        from ai_crypto_trader_tpu.data.synthetic import generate_ohlcv

        d = generate_ohlcv(n=64, seed=1)
        series = {"BTCUSDC": from_dict(
            {k: v for k, v in d.items() if k != "regime"},
            symbol="BTCUSDC")}
        return FakeExchange(series, depth_capture=records)

    def test_replay_serves_captured_books(self):
        ex = self._exchange(self._records())
        book = ex.get_order_book("BTCUSDC", limit=5)
        assert book["captured"] is True
        assert book["bids"][0] == [100.0, 1.0]
        assert len(book["bids"]) == 5                  # limit respected
        again = ex.get_order_book("BTCUSDC", limit=5)
        assert again["bids"] == book["bids"]           # cursor-deterministic
        ex.advance()
        nxt = ex.get_order_book("BTCUSDC", limit=5)
        assert nxt["bids"][0] == [100.0, 2.0]          # clock picks records

    def test_replay_from_journal_path(self, tmp_path):
        from ai_crypto_trader_tpu.shell.stream import DepthCapture

        path = str(tmp_path / "cap.jsonl")
        dc = DepthCapture(path=path)
        for r in self._records(3):
            dc.ingest({"lastUpdateId": r["u"], "s": r["symbol"],
                       "bids": r["bids"], "asks": r["asks"]})
        dc.close()
        ex = self._exchange(path)
        assert ex.get_order_book("BTCUSDC")["captured"] is True

    def test_empty_capture_falls_back_to_synthetic(self):
        ex = self._exchange([])
        book = ex.get_order_book("BTCUSDC")
        assert "captured" not in book
        assert len(book["bids"]) == 20

    def test_other_symbols_capture_never_served_cross_symbol(self):
        """A capture holding only another symbol's books must NOT replay
        them under this symbol's price scale — synthetic fallback, not a
        silently mislabeled `captured` book."""
        ex = self._exchange(self._records(symbol="ETHUSDC"))
        book = ex.get_order_book("BTCUSDC")
        assert "captured" not in book
        # symbol-less hand-built records still serve any symbol
        anon = [dict(r, symbol="") for r in self._records(2)]
        ex2 = self._exchange(anon)
        assert ex2.get_order_book("BTCUSDC")["captured"] is True

    def test_analytics_consume_replayed_books(self):
        from ai_crypto_trader_tpu.ops.orderbook import orderbook_signal

        ex = self._exchange(self._records())
        book = ex.get_order_book("BTCUSDC", limit=8)
        sig = orderbook_signal(np.asarray(book["bids"], np.float32),
                               np.asarray(book["asks"], np.float32))
        assert sig["signal"] in ("BUY", "SELL", "NEUTRAL")


class TestBatchedOrderbook:
    def _books(self, B=6, N=12, seed=0):
        rng = np.random.default_rng(seed)
        px = 100.0 * (1.0 + 0.01 * rng.random((B, 1)))
        lv = np.arange(1, N + 1)
        bids = np.stack([np.broadcast_to(px - 0.01 * lv, (B, N)),
                         rng.random((B, N)) * 5 + 0.5], axis=-1)
        asks = np.stack([np.broadcast_to(px + 0.01 * lv, (B, N)),
                         rng.random((B, N)) * 5 + 0.5], axis=-1)
        return (jnp.asarray(bids, jnp.float32),
                jnp.asarray(asks, jnp.float32))

    def test_price_impact_batched_matches_loop(self):
        from ai_crypto_trader_tpu.ops.orderbook import price_impact

        bids, _ = self._books()
        sizes = jnp.asarray([100.0, 500.0, 2000.0], jnp.float32)
        batched = np.asarray(price_impact(bids, sizes))
        assert batched.shape == (6, 3)
        for b in range(6):
            np.testing.assert_array_equal(batched[b],
                                          np.asarray(price_impact(bids[b],
                                                                  sizes)))

    def test_find_walls_batched_matches_loop(self):
        from ai_crypto_trader_tpu.ops.orderbook import find_walls

        bids, _ = self._books(seed=3)
        batched = np.asarray(find_walls(bids))
        assert batched.shape == (6, 12)
        for b in range(6):
            np.testing.assert_array_equal(batched[b],
                                          np.asarray(find_walls(bids[b])))

    def test_pressure_metrics_batched_matches_loop(self):
        from ai_crypto_trader_tpu.ops.orderbook import pressure_metrics

        bids, asks = self._books(seed=5)
        batched = {k: np.asarray(v)
                   for k, v in pressure_metrics(bids, asks).items()}
        assert batched["microprice"].shape == (6,)
        for b in range(6):
            one = pressure_metrics(bids[b], asks[b])
            for k, v in one.items():
                np.testing.assert_allclose(batched[k][b], np.asarray(v),
                                           rtol=1e-6, err_msg=k)

    def test_extra_leading_dims(self):
        from ai_crypto_trader_tpu.ops.orderbook import price_impact

        bids, _ = self._books()
        stacked = jnp.stack([bids, bids])              # [2, 6, N, 2]
        sizes = jnp.asarray([100.0], jnp.float32)
        out = np.asarray(price_impact(stacked, sizes))
        assert out.shape == (2, 6, 1)
        np.testing.assert_array_equal(out[0], out[1])


class TestLobWorkloads:
    def test_backtest_under_stress_lob_dynamics(self):
        stats, summary = engine.backtest_under_stress(
            jax.random.PRNGKey(20), scenario=["calm", "liquidity_hole"],
            num_scenarios=6, steps=512, dynamics="lob")
        assert np.asarray(stats.final_balance).shape == (6,)
        assert summary["worst_final_balance"] > 0
        with pytest.raises(ValueError, match="unknown market dynamics"):
            engine.backtest_under_stress(jax.random.PRNGKey(0),
                                         num_scenarios=2, steps=64,
                                         dynamics="nope")

    def test_env_params_carry_book_features(self):
        from ai_crypto_trader_tpu.rl import env_reset, env_step, obs_size

        p, labels = engine.scenario_env_params(
            jax.random.PRNGKey(30), scenario=["calm", "spread_blowout"],
            num_scenarios=4, steps=512, episode_len=32, dynamics="lob")
        assert p.obs_table.shape == (4, 512, 10)       # 8 market + 2 book
        assert obs_size(p) == 12
        keys = jax.random.split(jax.random.PRNGKey(0), 16)
        states, obs = jax.vmap(lambda k: env_reset(p, k))(keys)
        assert obs.shape == (16, 12)
        s2, obs2, r, done = jax.vmap(
            lambda s: env_step(p, s, jnp.asarray(1)))(states)
        assert obs2.shape == (16, 12)
        assert np.isfinite(np.asarray(r)).all()
        # the spread column actually varies across scenarios (blowout
        # rows see wider books than calm rows)
        spread_col = np.asarray(p.obs_table[..., 8])
        assert spread_col.max() > 2.0 * max(spread_col.min(), 1e-9)

    def test_default_env_unchanged(self):
        from ai_crypto_trader_tpu.rl import obs_size
        from ai_crypto_trader_tpu.rl.env import OBS_SIZE

        p, _ = engine.scenario_env_params(
            jax.random.PRNGKey(31), scenario="calm", num_scenarios=2,
            steps=256, episode_len=32)
        assert p.obs_table.shape[-1] == 8
        assert obs_size(p) == OBS_SIZE

    def test_dqn_trains_on_book_feature_env(self):
        from ai_crypto_trader_tpu.rl import (DQNConfig, dqn_init, obs_size,
                                             train_iterations)

        p, _ = engine.scenario_env_params(
            jax.random.PRNGKey(40), scenario=["calm", "liquidity_hole"],
            num_scenarios=4, steps=384, episode_len=64, dynamics="lob")
        cfg = DQNConfig(num_envs=8, rollout_len=4, state_size=obs_size(p))
        st = dqn_init(jax.random.PRNGKey(1), p, cfg)
        st, metrics = train_iterations(p, st, cfg, n_iters=2)
        assert np.isfinite(np.asarray(metrics["loss"])).all()
