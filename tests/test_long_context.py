"""Long-context transformer: mesh-sharded forward/backward vs single-device.

The same parameters applied to the same [T, F] series must produce the same
predictions whether the sequence axis lives on one device (dense attention)
or is ring-sharded 8 ways — and a full gradient step through the ring must
match the dense gradient (models/long_context.py; the reference caps its
transformer at 60 candles, `neural_network_service.py:530-586`)."""

import numpy as np
import pytest

import jax
import jax.flatten_util
import jax.numpy as jnp

from ai_crypto_trader_tpu.models.long_context import (
    LongContextTransformer,
    long_context_loss,
)

# Slow tier (VERDICT r4 next#3): golden-parity / end-to-end /
# training / sharded-compile suite — deselected by the default
# run, executed via `pytest -m slow`.
pytestmark = pytest.mark.slow


T, F = 512, 8


@pytest.fixture(scope="module")
def series():
    rng = np.random.default_rng(9)
    x = jnp.asarray(rng.normal(0, 1, (T, F)), jnp.float32)
    close = 100.0 * np.cumprod(1 + rng.normal(0, 0.01, T))
    ret = np.full((T, 1), np.nan, np.float32)
    ret[:-1, 0] = np.diff(close) / close[:-1]
    return x, jnp.asarray(ret)


@pytest.fixture(scope="module")
def params(series):
    x, _ = series
    model = LongContextTransformer(d_model=32, num_heads=4, num_blocks=2,
                                   ff_dim=64)
    return model.init(jax.random.PRNGKey(0), x)


class TestShardedForwardParity:
    def test_predictions_match_dense(self, mesh8, series, params):
        x, _ = series
        dense = LongContextTransformer(32, 4, 2, 64, mesh=None)
        ring = LongContextTransformer(32, 4, 2, 64, mesh=mesh8)
        want = np.asarray(dense.apply(params, x)["mean"])
        got = np.asarray(ring.apply(params, x)["mean"])
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)

    def test_causal_prefix_invariance(self, mesh8, series, params):
        """Prediction at position t must not change when the future half of
        the series is replaced — across the sharded path."""
        x, _ = series
        ring = LongContextTransformer(32, 4, 2, 64, mesh=mesh8)
        base = np.asarray(ring.apply(params, x)["mean"])
        x2 = x.at[T // 2:].set(0.0)
        pert = np.asarray(ring.apply(params, x2)["mean"])
        np.testing.assert_allclose(pert[: T // 2], base[: T // 2],
                                   rtol=2e-4, atol=2e-4)


class TestShardedTraining:
    def test_gradients_match_dense(self, mesh8, series, params):
        x, y = series
        dense = LongContextTransformer(32, 4, 2, 64, mesh=None)
        ring = LongContextTransformer(32, 4, 2, 64, mesh=mesh8)
        gd = jax.grad(lambda p: long_context_loss(dense, p, x, y))(params)
        gr = jax.grad(lambda p: long_context_loss(ring, p, x, y))(params)
        flat_d, _ = jax.flatten_util.ravel_pytree(gd)
        flat_r, _ = jax.flatten_util.ravel_pytree(gr)
        np.testing.assert_allclose(np.asarray(flat_r), np.asarray(flat_d),
                                   rtol=5e-3, atol=5e-4)

    def test_loss_decreases_under_sgd(self, mesh8, series, params):
        x, y = series
        ring = LongContextTransformer(32, 4, 2, 64, mesh=mesh8)
        loss_fn = jax.jit(lambda p: long_context_loss(ring, p, x, y))
        grad_fn = jax.jit(jax.grad(lambda p: long_context_loss(ring, p, x, y)))
        p = params
        l0 = float(loss_fn(p))
        for _ in range(5):
            g = grad_fn(p)
            p = jax.tree.map(lambda a, b: a - 0.05 * b, p, g)
        l1 = float(loss_fn(p))
        assert np.isfinite(l0) and np.isfinite(l1) and l1 < l0

    def test_masked_targets_ignored(self, series, params):
        """NaN targets contribute nothing: blowing up a masked position
        leaves the loss unchanged; blowing up a live one does not."""
        x, y = series
        dense = LongContextTransformer(32, 4, 2, 64)
        base = float(long_context_loss(dense, params, x, y))
        assert np.isfinite(base)
        y_nan_tail = y.at[-10:].set(jnp.nan)
        masked = float(long_context_loss(dense, params, x, y_nan_tail))
        assert np.isfinite(masked)          # NaNs never poison the loss
        y_big = y.at[0, 0].set(1e3)
        live = float(long_context_loss(dense, params, x, y_big))
        assert live > base                  # a live target still counts
