"""Monte-Carlo engine tests: statistical correctness of GBM/bootstrap and
parity of the statistics block with a NumPy re-computation (the formulas of
`services/monte_carlo_service.py:302-336`)."""

import numpy as np
import jax
import jax.numpy as jnp

from ai_crypto_trader_tpu import mc


KEY = jax.random.PRNGKey(42)


class TestGBM:
    def test_shape_and_initial(self):
        paths = mc.simulate_gbm(KEY, 100.0, 0.05, 0.3, days=30, num_sims=512)
        assert paths.shape == (512, 30)
        np.testing.assert_allclose(np.asarray(paths[:, 0]), 100.0)

    def test_terminal_mean(self):
        # E[S_T] = S0 * exp(mu * T) for GBM
        days, n = 252, 20_000
        paths = mc.simulate_gbm(KEY, 100.0, 0.10, 0.2, days=days, num_sims=n)
        t_years = (days - 1) / 252.0
        expected = 100.0 * np.exp(0.10 * t_years)
        got = float(jnp.mean(paths[:, -1]))
        assert abs(got - expected) / expected < 0.02

    def test_zero_vol_is_deterministic(self):
        paths = mc.simulate_gbm(KEY, 100.0, 0.10, 0.0, days=10, num_sims=4)
        np.testing.assert_allclose(np.asarray(paths[0]), np.asarray(paths[3]))


class TestBootstrap:
    def test_resamples_historical(self):
        rets = jnp.asarray(np.float32([0.01, -0.02, 0.005, 0.03, -0.01]))
        paths = mc.simulate_bootstrap(KEY, 50.0, rets, days=20, num_sims=256)
        assert paths.shape == (256, 20)
        step_rets = np.diff(np.log(np.asarray(paths)), axis=1)
        # every step return must be one of the historical log returns
        assert np.isin(step_rets.round(5), np.asarray(rets).round(5)).mean() > 0.999


class TestStatistics:
    def test_against_numpy_oracle(self):
        paths = mc.simulate_gbm(KEY, 100.0, 0.05, 0.5, days=30, num_sims=2_000)
        stats = {k: np.asarray(v) for k, v in mc.path_statistics(paths, 100.0).items()}
        p = np.asarray(paths)
        final = p[:, -1]
        pct = (final / 100.0 - 1) * 100
        np.testing.assert_allclose(stats["var"], np.percentile(pct, 5), rtol=1e-3)
        cvar_ref = pct[pct <= np.percentile(pct, 5)].mean()
        np.testing.assert_allclose(stats["cvar"], cvar_ref, rtol=5e-3)
        np.testing.assert_allclose(stats["prob_profit"], (final > 100).mean(), atol=1e-6)
        rm = np.maximum.accumulate(p, axis=1)
        dd = ((rm - p) / rm).max(axis=1)
        np.testing.assert_allclose(stats["max_drawdown_mean"], dd.mean(), rtol=1e-4)
        assert stats["cvar"] <= stats["var"] + 1e-6

    def test_run_simulation_scenarios(self, rng):
        rets = rng.normal(0.0005, 0.02, 500).astype(np.float32)
        out_base = mc.run_simulation(KEY, 100.0, rets, days=30, num_sims=500, scenario="base")
        out_vol = mc.run_simulation(KEY, 100.0, rets, days=30, num_sims=500, scenario="volatile")
        assert float(out_vol["sigma"]) > float(out_base["sigma"]) * 1.9
        out_bear = mc.run_simulation(KEY, 100.0, rets, days=30, num_sims=500, scenario="bear")
        assert float(out_bear["mu"]) == -float(out_base["mu"])


class TestPortfolio:
    def test_weighted_sums(self):
        w = jnp.asarray([0.5, 0.3, 0.2])
        er = jnp.asarray([0.10, 0.05, -0.02])
        v = jnp.asarray([0.08, 0.12, 0.2])
        cv = jnp.asarray([0.1, 0.15, 0.25])
        out = mc.portfolio_stats(w, er, v, cv)
        np.testing.assert_allclose(float(out["expected_return"]), 0.061, rtol=1e-5)

    def test_correlated_joint(self):
        n_assets = 3
        cov = np.array([[0.04, 0.01, 0.0], [0.01, 0.09, 0.02], [0.0, 0.02, 0.16]], np.float32)
        out = mc.simulate_portfolio_correlated(
            KEY, jnp.ones(n_assets) * 100.0, jnp.asarray([0.05, 0.03, 0.08]),
            jnp.asarray(cov), jnp.asarray([0.4, 0.4, 0.2]), days=30, num_sims=256)
        assert out.shape == (256, 30)
        np.testing.assert_allclose(np.asarray(out[:, 0]), 1.0, rtol=1e-5)
