"""The mesh runtime observatory (utils/meshprof.py) — ISSUE 12 tier-1.

Pins the contracts:
  * RecompileSentinel: a warm repeat attributes ZERO compiles; a forced
    shape change after warmup is COUNTED and ALERTED (the negative test
    the zero-recompile contract always lacked); cold-marked windows
    (expected rebuilds) never count.
  * TransferSentinel: a watch window exiting on a transfer-guard-shaped
    error counts the violation per program and feeds the
    UnintendedHostTransfer alert; unrelated errors never count.
  * Layout cards: pop 10 on the 8-way mesh records pad_fraction 0.375
    (the analytic value — 6 pad rows / 16 lanes), 2 members/device, and
    the exact all-gather byte volume; gauges land in the registry.
  * Memory imbalance: per-device skew folds to max/mean and drives
    DeviceMemoryImbalance only on multi-device hosts.
  * Alert coherence (the PR 1 suite pattern): the four mesh alerts exist
    in BOTH rule engines and reference only emitted series.
  * Launcher integration + the acceptance soak: a paper system with the
    observatory ON ticks at steady state with zero steady recompiles,
    zero guarded transfers, and a /state.json `mesh` block carrying the
    partitioner layout.
"""

import asyncio
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ai_crypto_trader_tpu.parallel.mesh import make_mesh
from ai_crypto_trader_tpu.parallel.partitioner import (
    MeshPartitioner,
    SingleDevicePartitioner,
    get_partitioner,
)
from ai_crypto_trader_tpu.utils import meshprof
from ai_crypto_trader_tpu.utils.alerts import AlertManager
from ai_crypto_trader_tpu.utils.metrics import MetricsRegistry

REPO = os.path.join(os.path.dirname(__file__), "..")


class TestRecompileSentinel:
    def test_warm_repeat_attributes_zero_compiles(self):
        mp = meshprof.MeshProf(guard_transfers=False)
        f = jax.jit(lambda x: x * 2 + 1)
        with meshprof.use(mp):
            with meshprof.watch("tick_engine"):
                f(jnp.ones(7)).block_until_ready()      # warmup window
            with meshprof.watch("tick_engine"):
                f(jnp.ones(7)).block_until_ready()      # steady repeat
        assert mp.recompiles.steady_total() == 0
        assert mp.recompiles.windows["tick_engine"] == 2
        assert mp.recompiles.alerted == []

    def test_forced_shape_change_counted_and_alerted(self):
        """THE negative test (ISSUE 12 satellite): after warmup, a shape
        change on a hot program is a counted steady-state recompile and
        fires SteadyStateRecompile in the in-process rule engine."""
        mp = meshprof.MeshProf(guard_transfers=False)
        f = jax.jit(lambda x: x * 3 - 1)
        with meshprof.use(mp):
            with meshprof.watch("ga_scan"):
                f(jnp.ones(5)).block_until_ready()      # warmup
            with meshprof.watch("ga_scan"):
                f(jnp.ones(9)).block_until_ready()      # forced re-trace
        assert mp.recompiles.steady_total() > 0
        assert "ga_scan" in mp.recompiles.alerted
        fired = AlertManager(now_fn=lambda: 0.0).evaluate(mp.alert_state())
        assert "SteadyStateRecompile" in {a["name"] for a in fired}

    def test_cold_windows_never_count(self):
        """An expected rebuild (fresh market window, new scale knob) rides
        cold=True — by design it compiles, by design it must not page."""
        mp = meshprof.MeshProf(guard_transfers=False)
        f = jax.jit(lambda x: x + 2)
        with meshprof.use(mp):
            with meshprof.watch("sim_sweep"):
                f(jnp.ones(4)).block_until_ready()
            with meshprof.watch("sim_sweep", cold=True):
                f(jnp.ones(6)).block_until_ready()      # expected re-trace
        assert mp.recompiles.steady_total() == 0
        assert mp.recompiles.alerted == []
        # ...but the total compile attribution still recorded the work
        assert mp.recompiles.compiles.get("sim_sweep", 0) >= 0

    def test_non_hot_program_counts_but_never_alerts(self):
        mp = meshprof.MeshProf(guard_transfers=False)
        f = jax.jit(lambda x: x * 5)
        with meshprof.use(mp):
            with meshprof.watch("side_program"):
                f(jnp.ones(3)).block_until_ready()
            with meshprof.watch("side_program"):
                f(jnp.ones(11)).block_until_ready()
        assert mp.recompiles.steady.get("side_program", 0) > 0
        assert mp.recompiles.alerted == []

    def test_counters_land_in_metrics(self):
        reg = MetricsRegistry()
        mp = meshprof.MeshProf(metrics=reg, guard_transfers=False)
        f = jax.jit(lambda x: x - 4)
        with meshprof.use(mp):
            with meshprof.watch("tick_engine"):
                f(jnp.ones(2)).block_until_ready()
            with meshprof.watch("tick_engine"):
                f(jnp.ones(13)).block_until_ready()
        text = reg.exposition()
        assert "mesh_steady_recompiles_total" in text
        assert 'program="tick_engine"' in text


class _FakeGuardError(RuntimeError):
    """The shape of jaxlib's transfer-guard error: the PJRT CPU client
    never trips the guard (device→host is zero-copy there), so the
    counting path is exercised with the error text the real guard
    raises on accelerators."""


class TestTransferSentinel:
    def test_violation_error_shape_recognized(self):
        err = _FakeGuardError(
            "Disallowed device-to-host transfer: aval=ShapedArray(f32[8])")
        assert meshprof.is_transfer_violation(err)
        assert not meshprof.is_transfer_violation(ValueError("boom"))

    def test_watch_counts_violation_and_alerts(self):
        mp = meshprof.MeshProf()
        with meshprof.use(mp):
            with pytest.raises(_FakeGuardError):
                with meshprof.watch("tick_engine"):
                    raise _FakeGuardError(
                        "Disallowed device-to-host transfer of x")
        assert mp.transfers.violations["tick_engine"] == 1
        state = mp.alert_state()
        assert state["guarded_transfer_programs"] == ["tick_engine"]
        fired = AlertManager(now_fn=lambda: 0.0).evaluate(state)
        assert "UnintendedHostTransfer" in {a["name"] for a in fired}

    def test_unrelated_errors_never_count(self):
        mp = meshprof.MeshProf()
        with meshprof.use(mp):
            with pytest.raises(ValueError):
                with meshprof.watch("ga_scan"):
                    raise ValueError("not a transfer")
        assert mp.transfers.total() == 0
        # an aborted window is not a completed warm window either
        assert mp.recompiles.windows.get("ga_scan", 0) == 0

    def test_guard_auto_disarms_after_first_violation(self):
        """A deterministic stray pull must be counted ONCE, not abort
        every subsequent dispatch into a crash-looped stage: after the
        first counted violation the guard stops arming for that program
        (the alert stays latched; other programs stay guarded)."""
        mp = meshprof.MeshProf()
        with meshprof.use(mp):
            with mp.watch("tick_engine") as w0:
                assert w0._guard is not None         # armed
            with pytest.raises(_FakeGuardError):
                with mp.watch("tick_engine"):
                    raise _FakeGuardError(
                        "Disallowed device-to-host transfer of x")
            with mp.watch("tick_engine") as w1:
                assert w1._guard is None             # disarmed: counted,
                #                                      alerted, not fatal
            with mp.watch("ga_scan") as w2:
                assert w2._guard is not None         # others still armed
        assert mp.transfers.violations["tick_engine"] == 1

    def test_disabled_module_helpers_are_noops(self):
        meshprof.disable()
        assert meshprof.active() is None
        with meshprof.watch("anything") as w:
            assert w is None
        with meshprof.allow_transfers() as a:
            assert a is None

    def test_sanctioned_host_read_inside_guarded_watch(self):
        """The host_read seams re-enter an allow scope inside the watch's
        disallow guard — the one sanctioned sync must never count (on the
        CPU backend the guard is inert either way; this pins the scope
        nesting doesn't raise or miscount)."""
        mp = meshprof.MeshProf()
        f = jax.jit(lambda x: x * 2)
        with meshprof.use(mp):
            with meshprof.watch("tick_engine"):
                out = f(jnp.ones(3))
                with meshprof.allow_transfers():
                    np.asarray(out)
        assert mp.transfers.total() == 0


class TestLayoutCards:
    def test_pop10_on_8way_mesh_matches_analytic(self, mesh8):
        """The acceptance number: pop 10 on 8 devices pads 6 rows onto 16
        lanes = 37.5% wasted — measured by the card, not assumed."""
        reg = MetricsRegistry()
        mp = meshprof.MeshProf(metrics=reg)
        with meshprof.use(mp):
            pe = MeshPartitioner(mesh8).population_eval(
                lambda t: {"sq": t["x"] ** 2, "sum": t["x"].sum(-1)},
                name="ga_scan")
            pe({"x": jnp.arange(40.0).reshape(10, 4)})
        card = mp.layouts["ga_scan"]
        assert card.population == 10 and card.pad == 6
        assert card.devices == 8
        assert abs(card.pad_fraction - 0.375) < 1e-12
        assert card.members_per_device == 2.0
        # all-gather bytes: sq [16,4] f32 + sum [16] f32, each received
        # from the 7 other devices
        assert card.collective_bytes == (16 * 4 * 4 + 16 * 4) * 7
        assert len(card.device_names) == 8
        text = reg.exposition()
        assert 'crypto_trader_tpu_mesh_pad_fraction{program="ga_scan"} '\
               '0.375' in text
        assert "mesh_device_members" in text
        # pad waste above the 25% threshold fires MeshPaddingWasteHigh
        fired = AlertManager(now_fn=lambda: 0.0).evaluate(mp.alert_state())
        assert "MeshPaddingWasteHigh" in {a["name"] for a in fired}

    def test_divisible_population_has_zero_pad(self, mesh8):
        mp = meshprof.MeshProf()
        with meshprof.use(mp):
            pe = MeshPartitioner(mesh8).population_eval(
                lambda t: t["x"] * 2, name="population_sweep")
            pe({"x": jnp.ones((16, 3))})
        card = mp.layouts["population_sweep"]
        assert card.pad == 0 and card.pad_fraction == 0.0
        fired = AlertManager(now_fn=lambda: 0.0).evaluate(mp.alert_state())
        assert "MeshPaddingWasteHigh" not in {a["name"] for a in fired}

    def test_single_device_card_records_trivial_layout(self):
        mp = meshprof.MeshProf()
        with meshprof.use(mp):
            pe = SingleDevicePartitioner().population_eval(
                lambda t: t["x"] + 1, name="structure_pool")
            pe({"x": jnp.ones((6, 2))})
        card = mp.layouts["structure_pool"]
        assert (card.population, card.pad, card.devices) == (6, 0, 1)
        assert card.collective_bytes == 0

    def test_scanned_ga_records_layout_and_matches_gauge(self, mesh8):
        """End-to-end through run_ga: the partitioned eval inside the
        scanned program records the ragged layout at trace time and the
        published gauge matches the analytic value."""
        from test_partitioner import _cheap_fitness

        from ai_crypto_trader_tpu.config import GAParams
        from ai_crypto_trader_tpu.evolve import run_ga

        def fitness(p):                   # fresh closure → fresh program
            return _cheap_fitness(p)

        reg = MetricsRegistry()
        mp = meshprof.MeshProf(metrics=reg)
        cfg = GAParams(population_size=10, generations=2, elite_size=2)
        with meshprof.use(mp):
            run_ga(jax.random.PRNGKey(3), fitness, cfg,
                   partitioner=MeshPartitioner(mesh8))
        assert abs(mp.layouts["ga_scan"].pad_fraction - 0.375) < 1e-12
        assert mp.transfers.total() == 0
        # the compile run is cold by construction (fresh program cache
        # entry) — nothing may count as a steady-state recompile
        assert mp.recompiles.steady_total() == 0

    def test_trial_assignment_accounting(self):
        reg = MetricsRegistry()
        mp = meshprof.MeshProf(metrics=reg)
        with meshprof.use(mp):
            for i in range(5):
                meshprof.record_trial(f"dev{i % 2}")
        assert mp.trial_assignments == {"dev0": 3, "dev1": 2}
        assert "mesh_trial_assignments_total" in reg.exposition()


class TestMemoryImbalance:
    def _sample(self, sizes):
        return {f"d{i}": {"count": 1, "bytes": b}
                for i, b in enumerate(sizes)}

    def test_skew_fold_and_alert(self):
        reg = MetricsRegistry()
        mp = meshprof.MeshProf(metrics=reg)
        mp.observe_memory(self._sample([100, 100, 100, 900]))
        assert mp.last_imbalance == pytest.approx(900 / 300)
        assert mp.last_device_count == 4
        fired = AlertManager(now_fn=lambda: 0.0).evaluate(mp.alert_state())
        assert "DeviceMemoryImbalance" in {a["name"] for a in fired}
        text = reg.exposition()
        assert "mesh_memory_imbalance" in text
        assert "crypto_trader_tpu_mesh_devices 4" in text

    def test_balanced_and_single_device_stay_silent(self):
        mp = meshprof.MeshProf()
        mp.observe_memory(self._sample([500, 500]))
        names = {a["name"] for a in
                 AlertManager(now_fn=lambda: 0.0).evaluate(mp.alert_state())}
        assert "DeviceMemoryImbalance" not in names
        # a single device can hold 100% of bytes — never an imbalance
        mp.observe_memory(self._sample([12345]))
        names = {a["name"] for a in
                 AlertManager(now_fn=lambda: 0.0).evaluate(mp.alert_state())}
        assert "DeviceMemoryImbalance" not in names

    def test_self_sampling_without_devprof(self):
        mp = meshprof.MeshProf()
        out = mp.observe_memory(None)          # walks jax.live_arrays()
        assert isinstance(out, float)
        assert mp.last_device_count >= 1

    def test_reuses_devprof_watermark_sample(self):
        """With devprof active, the fold reads its watermark's newest
        sample instead of walking jax.live_arrays() a second time."""
        from ai_crypto_trader_tpu.utils import devprof

        dp = devprof.DevProf()
        dp.watermark.last = self._sample([100, 300])
        mp = meshprof.MeshProf()
        with devprof.use(dp):
            mp.observe_memory(None)
        assert mp.last_imbalance == pytest.approx(300 / 200)
        assert mp.last_device_count == 2


class TestPartitionerDescribe:
    def test_single_device_describe(self):
        d = SingleDevicePartitioner().describe()
        assert d["kind"] == "SingleDevicePartitioner"
        assert d["devices"] == 1
        assert d["platform"] == "cpu"

    def test_mesh_describe_carries_shape_and_kinds(self, mesh8):
        d = MeshPartitioner(mesh8).describe()
        assert d["devices"] == 8
        assert d["mesh_shape"] == {"data": 8, "model": 1}
        assert d["axis"] == "data"
        assert len(d["device_names"]) == 8
        assert d["device_kinds"]

    def test_get_partitioner_describe_never_raises(self):
        assert "kind" in get_partitioner().describe()


class TestMeshAlertCoherence:
    """Extends the PR 1 coherence suite: the four mesh alerts exist in
    BOTH rule engines, every referenced mesh_* series is emitted, and the
    recording group parses."""

    MESH_ALERTS = {"SteadyStateRecompile", "UnintendedHostTransfer",
                   "MeshPaddingWasteHigh", "DeviceMemoryImbalance"}

    def test_series_emitted_and_rules_in_both_engines(self):
        import re

        import yaml

        from test_observability import TestStackConfigCoherence

        from ai_crypto_trader_tpu.utils.alerts import default_rules

        emitted = TestStackConfigCoherence().emitted_series()
        new_series = {"mesh_steady_recompiles_total",
                      "mesh_program_compiles_total",
                      "mesh_guarded_transfers_total", "mesh_pad_fraction",
                      "mesh_population", "mesh_collective_bytes",
                      "mesh_compute_bytes", "mesh_device_members",
                      "mesh_memory_imbalance", "mesh_devices",
                      "mesh_trial_assignments_total"}
        missing = new_series - emitted
        assert not missing, f"mesh series not emitted: {missing}"

        rules = yaml.safe_load(
            open(os.path.join(REPO, "monitoring/alert_rules.yml")))
        alert_names = {r["alert"] for g in rules["groups"]
                      for r in g["rules"] if "alert" in r}
        assert self.MESH_ALERTS <= alert_names
        for g in rules["groups"]:
            for r in g["rules"]:
                if r.get("alert") in self.MESH_ALERTS:
                    for m in re.finditer(
                            r"crypto_trader_tpu_([a-z0-9_]+)", r["expr"]):
                        assert m.group(1) in emitted, m.group(1)
        in_process = {r.name for r in default_rules()}
        assert self.MESH_ALERTS <= in_process
        rec = yaml.safe_load(
            open(os.path.join(REPO, "monitoring/recording_rules.yml")))
        mesh_groups = [g for g in rec["groups"]
                       if g["name"] == "crypto_trader_tpu_mesh"]
        assert mesh_groups and mesh_groups[0]["rules"]

    def test_alert_resolution_lifecycle(self):
        mgr = AlertManager(now_fn=lambda: 0.0)
        fired = mgr.evaluate({"steady_recompile_programs": ["tick_engine"],
                              "mesh_pad_fraction_max": 0.375})
        names = {a["name"] for a in fired}
        assert {"SteadyStateRecompile", "MeshPaddingWasteHigh"} <= names
        mgr.evaluate({"steady_recompile_programs": [],
                      "mesh_pad_fraction_max": 0.0})
        assert "SteadyStateRecompile" not in mgr.active
        assert "MeshPaddingWasteHigh" not in mgr.active


def _paper_system(symbols=("BTCUSDC", "ETHUSDC"), n_hist=600, **kw):
    from ai_crypto_trader_tpu.data.ingest import from_dict
    from ai_crypto_trader_tpu.data.synthetic import generate_ohlcv
    from ai_crypto_trader_tpu.shell.exchange import make_exchange
    from ai_crypto_trader_tpu.shell.launcher import TradingSystem

    series = {}
    for i, sym in enumerate(symbols):
        d = generate_ohlcv(n=n_hist + 64, seed=11 + i)
        series[sym] = from_dict(
            {k: v for k, v in d.items() if k != "regime"}, symbol=sym)
    clock = {"t": 0.0}
    ex = make_exchange("fake", series=series, quote_balance=10_000.0)
    ex.advance(steps=n_hist)
    system = TradingSystem(ex, list(symbols), now_fn=lambda: clock["t"],
                           **kw)
    # same compiled shape bucket as tests/test_tick_engine.py /
    # test_stream.py (T=128): the soak exercises the REAL fused path
    # without paying a fresh whole-universe compile per test run
    system.monitor.kline_limit = 128
    return system, ex, clock


class TestLauncherIntegration:
    def test_meshprof_default_off(self):
        system, _, _ = _paper_system(enable_meshprof=False)
        try:
            assert system.meshprof is None
            assert meshprof.active() is None
        finally:
            system.shutdown()

    def test_steady_state_soak_and_state_json_mesh_block(self):
        """The acceptance soak (scaled to tier-1): the fused tick path
        under the observatory reports ZERO steady-state recompiles and
        ZERO guarded transfers across a steady run, the launcher exports
        the mesh gauges every tick, /state.json carries a `mesh` block
        with the partitioner layout, and shutdown deactivates."""
        from ai_crypto_trader_tpu.shell.dashboard_server import (
            DashboardServer,
        )

        system, ex, clock = _paper_system(enable_meshprof=True)
        server = DashboardServer(system, port=0).start()
        try:
            assert system.meshprof is meshprof.active()

            async def soak():
                for _ in range(8):
                    ex.advance(steps=1)
                    clock["t"] += 60.0
                    await system.tick()

            asyncio.run(soak())
            mp = system.meshprof
            assert mp.recompiles.steady_total() == 0, \
                mp.recompiles.status()
            assert mp.transfers.total() == 0
            # the fused tick path completed warm watch windows
            assert mp.recompiles.windows.get("tick_engine", 0) >= 2
            # per-tick export ran: imbalance + devices gauges live
            text = system.metrics.exposition()
            assert "mesh_devices" in text
            assert "mesh_memory_imbalance" in text
            # alert state is quiet at steady state
            names = {a["name"] for a in AlertManager(
                now_fn=lambda: 0.0).evaluate(system._alert_state())}
            assert not (names & TestMeshAlertCoherence.MESH_ALERTS), names
            # /state.json mesh block: partitioner layout + sentinel state
            state = server.state()
            assert "mesh" in state
            assert state["mesh"]["partitioner"]["devices"] >= 1
            assert "recompiles" in state["mesh"]
        finally:
            server.stop()
            system.shutdown()
        assert meshprof.active() is None

    def test_state_json_partitioner_block_without_observatory(self):
        """Satellite: the active layout is visible even with meshprof OFF
        — operators can read mesh shape/device kinds without a REPL."""
        from ai_crypto_trader_tpu.shell.dashboard_server import (
            DashboardServer,
        )

        system, ex, clock = _paper_system(enable_meshprof=False)
        server = DashboardServer(system, port=0)   # state() without start:
        try:                                       # stop() must not hang
            state = server.state()
            assert "mesh" in state
            assert state["mesh"]["partitioner"]["kind"] in (
                "SingleDevicePartitioner", "MeshPartitioner")
            # observatory off → no sentinel block, just the layout
            assert "recompiles" not in state["mesh"]
        finally:
            server.stop()
            system.shutdown()


class TestCliSurface:
    def test_cmd_mesh_prints_layout_and_pad_math(self, capsys):
        from ai_crypto_trader_tpu.cli import cmd_mesh

        class A:
            pop = 10
            url = None

        cmd_mesh(A())
        out = capsys.readouterr().out
        assert "partitioner" in out
        assert "pad_fraction" in out

    def test_cmd_status_local_fallback(self, capsys):
        from ai_crypto_trader_tpu.cli import cmd_status

        class A:
            url = None

        cmd_status(A())
        out = capsys.readouterr().out
        assert '"live": false' in out
        assert "partitioner" in out
