"""Model zoo: forward shapes for all 9 architectures, training convergence,
prediction, HPO, and feature importance."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from ai_crypto_trader_tpu.models import (
    MODEL_REGISTRY,
    build_model,
    feature_importance,
    fit_scaler,
    make_windows,
    optimize_hyperparameters,
    predict_prices,
    train_model,
)

# Slow tier (VERDICT r4 next#3): golden-parity / end-to-end /
# training / sharded-compile suite — deselected by the default
# run, executed via `pytest -m slow`.
pytestmark = pytest.mark.slow


KEY = jax.random.PRNGKey(0)


def _features(n=300, f=4, seed=0):
    rng = np.random.default_rng(seed)
    t = np.arange(n)
    base = 100 + 10 * np.sin(t / 20) + rng.normal(0, 0.5, n)
    cols = [base] + [rng.normal(0, 1, n) for _ in range(f - 1)]
    return np.stack(cols, axis=1).astype(np.float32)


class TestZoo:
    @pytest.mark.parametrize("mt", MODEL_REGISTRY)
    def test_forward_shapes(self, mt):
        model = build_model(mt, units=16)
        x = jnp.zeros((2, 20, 4))
        params = model.init(KEY, x, False)
        out = model.apply(params, x, False)
        expected_h = 3 if mt == "multitask" else 1
        assert out["mean"].shape == (2, expected_h)
        if mt == "probabilistic":
            assert out["log_sigma"].shape == (2, 1)

    def test_dropout_only_in_train(self):
        model = build_model("lstm", units=16, dropout=0.5)
        x = jnp.ones((2, 20, 4))
        params = model.init(KEY, x, False)
        a = model.apply(params, x, False)
        b = model.apply(params, x, False)
        np.testing.assert_allclose(np.asarray(a["mean"]), np.asarray(b["mean"]))
        c = model.apply(params, x, True, rngs={"dropout": KEY})
        assert not np.allclose(np.asarray(a["mean"]), np.asarray(c["mean"]))


class TestWindows:
    def test_shapes_and_targets(self):
        f = _features(100)
        X, y = make_windows(f, seq_len=10, horizons=(1, 3))
        assert X.shape == (88, 10, 4) and y.shape == (88, 2)
        np.testing.assert_allclose(y[0, 0], f[10, 0])
        np.testing.assert_allclose(y[0, 1], f[12, 0])

    def test_too_short_raises(self):
        with pytest.raises(ValueError):
            make_windows(_features(10), seq_len=20)

    def test_scaler_roundtrip(self):
        f = _features(50)
        s = fit_scaler(f)
        scaled = s.transform(jnp.asarray(f))
        assert float(scaled.min()) >= 0 and float(scaled.max()) <= 1.0001
        back = s.inverse(scaled[:, 0], 0)
        np.testing.assert_allclose(np.asarray(back), f[:, 0], rtol=1e-5)


class TestTraining:
    def test_loss_decreases_and_early_stops(self):
        f = _features(250)
        r = train_model(KEY, f, "lstm", seq_len=16, units=16, epochs=12,
                        batch_size=32, early_stopping_patience=12)
        losses = [h["loss"] for h in r.history]
        assert losses[-1] < losses[0]
        assert r.best_val_loss < np.inf
        out = predict_prices(r, f, seq_len=16)
        assert np.isfinite(out["predicted_price"]).all()
        assert 0.0 < out["confidence"] <= 1.0

    def test_multitask_and_probabilistic(self):
        f = _features(200)
        r = train_model(KEY, f, "multitask", seq_len=16, units=16, epochs=2)
        assert np.isfinite(r.best_val_loss)
        r = train_model(KEY, f, "probabilistic", seq_len=16, units=16, epochs=2)
        out = predict_prices(r, f, seq_len=16)
        assert "predicted_std" in out
        assert np.all(np.asarray(out["predicted_std"]) > 0)

    def test_scaler_fit_excludes_validation_rows(self):
        """No look-ahead: a price spike confined to the val tail must not
        influence the scaler."""
        f = _features(200)
        f[-20:, 0] += 1000.0  # future-only spike
        r = train_model(KEY, f, "lstm", seq_len=16, units=8, epochs=1,
                        val_fraction=0.2)
        train_rows = 200 - int(200 * 0.2)
        assert float(r.scaler.max[0]) <= f[:train_rows, 0].max() + 1e-3

    def test_lr_plateau_reduces(self):
        f = _features(150)
        r = train_model(KEY, f, "lstm", seq_len=16, units=8, epochs=15,
                        reduce_lr_patience=1, early_stopping_patience=15,
                        learning_rate=1e-3)
        lrs = [h["lr"] for h in r.history]
        assert min(lrs) <= max(lrs)  # monotone non-increasing schedule
        assert all(b <= a + 1e-12 for a, b in zip(lrs, lrs[1:]))


class TestHPO:
    def test_two_trials(self):
        f = _features(150)
        out = optimize_hyperparameters(KEY, f, n_trials=2, rung_epochs=(1, 2),
                                       seq_len=16)
        assert len(out["trials"]) == 2
        assert np.isfinite(out["best_val_loss"])
        assert out["best_params"]["model_type"] in MODEL_REGISTRY

    def test_trials_farm_over_partitioner_devices(self, mesh8):
        """HPO with a MeshPartitioner round-robins trial programs over the
        mesh devices via jax.default_device — results stay valid and every
        trial's arrays land on a real device."""
        from ai_crypto_trader_tpu.parallel import MeshPartitioner

        f = _features(150)
        out = optimize_hyperparameters(
            KEY, f, n_trials=2, rung_epochs=(1, 1), seq_len=16,
            sampler="random", partitioner=MeshPartitioner(mesh8))
        assert len(out["trials"]) == 2
        assert np.isfinite(out["best_val_loss"])

    def test_tpe_sampler_concentrates_on_good_region(self):
        """Pure-sampler test (no training): on a synthetic objective whose
        optimum is (lr≈1e-3, dropout≈0.2, units=64), TPE proposals must land
        closer to the optimum than the random prior does on average."""
        from ai_crypto_trader_tpu.models.hpo import _sample_trial, suggest_tpe

        rng = np.random.default_rng(3)

        def objective(t):
            return (abs(np.log(t["learning_rate"]) - np.log(1e-3))
                    + abs(t["dropout"] - 0.2) * 4.0
                    + (0.0 if t["units"] == 64 else 1.0))

        history = []
        for _ in range(30):
            t = _sample_trial(rng) if len(history) < 8 \
                else suggest_tpe(history, rng)
            history.append({"trial": t, "val_loss": objective(t)})
        tpe_losses = [h["val_loss"] for h in history[8:]]
        random_losses = [objective(_sample_trial(rng)) for _ in range(200)]
        assert np.mean(tpe_losses) < np.mean(random_losses)

    def test_tpe_handles_tiny_history(self):
        from ai_crypto_trader_tpu.models.hpo import _sample_trial, suggest_tpe

        rng = np.random.default_rng(0)
        h = [{"trial": _sample_trial(rng), "val_loss": 1.0}]
        t = suggest_tpe(h, rng)
        assert set(t) == {"model_type", "units", "dropout", "learning_rate",
                          "batch_size"}
        assert 1e-4 <= t["learning_rate"] <= 1e-2
        assert 0.1 <= t["dropout"] <= 0.5


class TestImportance:
    def test_sums_to_one_and_ranks(self):
        f = _features(120)
        r = train_model(KEY, f, "lstm", seq_len=16, units=8, epochs=2)
        s = r.scaler.transform(jnp.asarray(f))
        X, _ = make_windows(np.asarray(s), seq_len=16)
        out = feature_importance(r.params, "lstm", jnp.asarray(X[:16]),
                                 feature_names=["close", "a", "b", "c"],
                                 model_kwargs=r.model_kwargs)
        w = np.asarray(list(out["importances"].values()))
        np.testing.assert_allclose(w.sum(), 1.0, rtol=1e-4)
        assert out["ranked"][0] in {"close", "a", "b", "c"}
