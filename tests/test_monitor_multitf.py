"""4-timeframe monitor default + hourly selection profiles
(VERDICT r3 weak #4 and #6).

The monitor must fetch/publish all four reference timeframes (1m/3m/5m/15m,
`market_monitor_service.py:150-217`) with the 0.6·1m + 0.4·5m trend blend
(:273) and per-interval indicator columns (:285-298); the selector must use
LEARNED per-hour performance profiles (:689-770) instead of a flat damp.
"""

import asyncio

import numpy as np
import pytest

from ai_crypto_trader_tpu.data.ingest import OHLCV
from ai_crypto_trader_tpu.data.synthetic import generate_ohlcv
from ai_crypto_trader_tpu.shell.bus import EventBus
from ai_crypto_trader_tpu.shell.exchange import FakeExchange
from ai_crypto_trader_tpu.shell.monitor import MarketMonitor

# Slow tier (VERDICT r4 next#3): golden-parity / end-to-end /
# training / sharded-compile suite — deselected by the default
# run, executed via `pytest -m slow`.
pytestmark = pytest.mark.slow


def long_series(n=2400, seed=7, symbol="BTCUSDC"):
    d = generate_ohlcv(n=n, seed=seed)
    return OHLCV(timestamp=np.arange(n, dtype=np.int64) * 60_000,
                 open=d["open"], high=d["high"], low=d["low"],
                 close=d["close"], volume=d["volume"] * 1000, symbol=symbol)


class TestFourTimeframes:
    def test_default_intervals_are_reference_four(self):
        bus = EventBus()
        ex = FakeExchange({"BTCUSDC": long_series()})
        mon = MarketMonitor(bus, ex)
        assert mon.intervals == ("1m", "3m", "5m", "15m")

    def test_all_frames_published_with_blend_and_columns(self):
        async def go():
            bus = EventBus()
            ex = FakeExchange({"BTCUSDC": long_series()})
            # 2400 base candles cover 64×15m resampled candles
            ex.advance("BTCUSDC", steps=2399)
            clock = {"t": 0.0}
            mon = MarketMonitor(bus, ex, symbols=["BTCUSDC"],
                                now_fn=lambda: clock["t"], kline_limit=64)
            q = bus.subscribe("market_updates")
            assert await mon.poll() == 1
            upd = q.get_nowait()["data"]
            # per-interval history stored for every frame (:150-217)
            for iv in ("1m", "3m", "5m", "15m"):
                rows = bus.get(f"historical_data_BTCUSDC_{iv}")
                assert rows is not None and len(rows) == 64
                # resampled frames span iv-many base minutes per bar
                if iv != "1m":
                    step = rows[1][0] - rows[0][0]
                    assert step == int(iv[:-1]) * 60_000
            # per-interval indicator columns (:285-298)
            for iv in ("3m", "5m", "15m"):
                assert f"rsi_{iv}" in upd
                assert f"macd_{iv}" in upd
                assert f"signal_{iv}" in upd
            assert "price_change_3m" in upd
            return upd

        asyncio.run(go())

    def test_trend_blend_is_1m_5m_weighted(self):
        """The published trend strength must equal 0.6·1m + 0.4·5m (:273),
        NOT a repeated fold over every secondary frame."""
        async def go():
            bus = EventBus()
            ex = FakeExchange({"BTCUSDC": long_series(seed=9)})
            ex.advance("BTCUSDC", steps=2399)
            mon = MarketMonitor(bus, ex, symbols=["BTCUSDC"],
                                now_fn=lambda: 0.0, kline_limit=64)
            await mon.poll(force=True)
            blended = bus.get("market_data_BTCUSDC")["trend_strength"]

            # a (1m,5m)-only monitor must produce the IDENTICAL blend —
            # 3m/15m contribute columns, never another fold into the trend
            b2 = EventBus()
            m2 = MarketMonitor(b2, ex, symbols=["BTCUSDC"],
                               now_fn=lambda: 0.0, kline_limit=64,
                               intervals=("1m", "5m"))
            await m2.poll(force=True)
            two_tf = b2.get("market_data_BTCUSDC")["trend_strength"]
            assert blended == pytest.approx(two_tf, rel=1e-6)
            # and it differs from the unblended 1m-only strength
            b3 = EventBus()
            m3 = MarketMonitor(b3, ex, symbols=["BTCUSDC"],
                               now_fn=lambda: 0.0, kline_limit=64,
                               intervals=("1m",))
            await m3.poll(force=True)
            only_1m = b3.get("market_data_BTCUSDC")["trend_strength"]
            assert blended != pytest.approx(only_1m, rel=1e-6)

        asyncio.run(go())


class TestHourlySelectionProfiles:
    def test_hourly_performance_built_from_trades(self):
        from ai_crypto_trader_tpu.strategy.selection import hourly_performance

        trades = ([{"pnl": 1.0, "closed_at": 3 * 3600 + i} for i in range(8)]
                  + [{"pnl": -1.0, "closed_at": 3 * 3600 + 100 + i}
                     for i in range(2)]
                  + [{"pnl": -1.0, "closed_at": 14 * 3600}])
        prof = hourly_performance(trades)
        assert prof["3"]["trade_count"] == 10
        assert prof["3"]["win_rate"] == pytest.approx(0.8)
        assert prof["14"]["win_rate"] == 0.0

    def test_learned_profile_moves_score(self):
        """±10% learned adjustment (:735): a strategy that historically wins
        at this hour outranks the same strategy scored at a losing hour."""
        from ai_crypto_trader_tpu.strategy.selection import StrategySelector

        sel = StrategySelector()
        strat = {"metrics": {"sharpe_ratio": 1.0},
                 "archetype": "trend_following",
                 "hourly_performance": {
                     "10": {"win_rate": 0.9, "trade_count": 50},
                     "11": {"win_rate": 0.1, "trade_count": 50},
                     "12": {"win_rate": 0.9, "trade_count": 5},  # thin data
                 }}
        good = sel.score_strategy(strat, hour_of_day=10)["combined"]
        bad = sel.score_strategy(strat, hour_of_day=11)["combined"]
        thin = sel.score_strategy(strat, hour_of_day=12)["combined"]
        base = sel.score_strategy(strat)["combined"]
        assert good > base > bad
        assert good - bad == pytest.approx(2 * 0.8 * 0.1, abs=1e-6)
        # <10 trades → no learned adjustment (:733), only window terms
        assert thin != good

    def test_time_window_adjustments(self):
        """High-volatility window rewards ATR handling (:740-749);
        low-activity window rewards low trade frequency (:752-758)."""
        from ai_crypto_trader_tpu.strategy.selection import StrategySelector

        sel = StrategySelector()
        strat = {"metrics": {"sharpe_ratio": 0.0},
                 "archetype": "trend_following",
                 "params": {"atr_multiplier": 2.0},
                 "avg_trades_per_hour": 0.0}
        base = sel.score_strategy(strat)["combined"]
        high_vol = sel.score_strategy(strat, hour_of_day=15)["combined"]
        low_act = sel.score_strategy(strat, hour_of_day=2)["combined"]
        neutral = sel.score_strategy(strat, hour_of_day=12)["combined"]
        assert high_vol == pytest.approx(base + 0.05, abs=1e-6)
        assert low_act == pytest.approx(base + 0.05, abs=1e-6)
        assert neutral == pytest.approx(base, abs=1e-6)

    def test_scores_clamped(self):
        from ai_crypto_trader_tpu.strategy.selection import StrategySelector

        sel = StrategySelector()
        strat = {"metrics": {"sharpe_ratio": 10.0, "max_drawdown_pct": 0.0},
                 "archetype": "breakout",
                 "hourly_performance": {"9": {"win_rate": 1.0,
                                              "trade_count": 100}}}
        out = sel.score_strategy(strat, regime="volatile", volatility=0.05,
                                 social_sentiment=1.0, hour_of_day=9)
        assert out["combined"] <= 1.0


class TestStructureView:
    def test_adopted_structure_drives_live_context(self):
        """The generator's hot-swapped structure must show up in the next
        market update: blend over the live combination scores + its
        thresholded signal (the structure search's own math, live)."""
        import asyncio
        import sys

        sys.path.insert(0, "tests")
        from test_shell import _series

        from ai_crypto_trader_tpu.shell.bus import EventBus
        from ai_crypto_trader_tpu.shell.exchange import FakeExchange
        from ai_crypto_trader_tpu.shell.monitor import MarketMonitor

        async def go():
            bus = EventBus()
            ex = FakeExchange({"BTCUSDC": _series()})
            ex.advance(steps=400)
            mon = MarketMonitor(bus, ex, symbols=["BTCUSDC"],
                                intervals=("1m",), now_fn=lambda: 0.0)
            await mon.poll()
            md = bus.get("market_data_BTCUSDC")
            assert "structure_signal" not in md       # nothing adopted yet

            bus.set("strategy_structure", {
                "rules": {"oscillator_consensus": 1.0,
                          "trend_confirmation": 1.0},
                "buy_threshold": 0.05, "sell_threshold": 0.05,
                "version": "v9"})
            await mon.poll(force=True)
            md = bus.get("market_data_BTCUSDC")
            assert md["structure_version"] == "v9"
            assert -1.0 <= md["structure_blend"] <= 1.0
            assert md["structure_signal"] in ("BUY", "SELL", "NEUTRAL")
            # thresholds applied to the blend
            if abs(md["structure_blend"]) >= 0.05:
                assert md["structure_signal"] != "NEUTRAL"

            # garbage payloads degrade to no structure columns
            bus.set("strategy_structure", {"rules": "garbage"})
            await mon.poll(force=True)
            assert "structure_signal" not in bus.get("market_data_BTCUSDC")

        asyncio.run(go())
