"""The multichip dryrun as a pytest (ISSUE 11 CI satellite).

`__graft_entry__.dryrun_multichip` exercises the four sharded programs on
an n-device mesh — dp×tp transformer training step, the Partitioner-
routed GA population sweep, the sequence-parallel scan, and ring-attention
training — and was previously only runnable by the driver (the
MULTICHIP_r0*.json artifacts).  Promoted to the slow tier so the sharded
paths rot loudly; skips cleanly when fewer than 8 devices are visible
(conftest.py forces 8 virtual CPU devices, so the skip only fires outside
the test harness)."""

import jax
import pytest

pytestmark = pytest.mark.slow


def test_dryrun_multichip_8devices(capsys):
    if jax.device_count() < 8:
        pytest.skip("needs >= 8 devices (virtual CPU mesh)")
    import __graft_entry__

    __graft_entry__.dryrun_multichip(8)
    out = capsys.readouterr().out
    assert "dryrun_multichip(8) OK" in out
