"""The multichip dryrun as a pytest (ISSUE 11 CI satellite).

`__graft_entry__.dryrun_multichip` exercises the four sharded programs on
an n-device mesh — dp×tp transformer training step, the Partitioner-
routed GA population sweep, the sequence-parallel scan, and ring-attention
training — and was previously only runnable by the driver (the
MULTICHIP_r0*.json artifacts).  Promoted to the slow tier so the sharded
paths rot loudly; skips cleanly when fewer than 8 devices are visible
(conftest.py forces 8 virtual CPU devices, so the skip only fires outside
the test harness)."""

import jax
import pytest

pytestmark = pytest.mark.slow


def test_dryrun_multichip_8devices(capsys):
    if jax.device_count() < 8:
        pytest.skip("needs >= 8 devices (virtual CPU mesh)")
    import __graft_entry__

    __graft_entry__.dryrun_multichip(8)
    out = capsys.readouterr().out
    assert "dryrun_multichip(8) OK" in out


def test_sharded_ga_locality_lands_in_bench_history(tmp_path):
    """ISSUE 12 CI satellite: the per-device locality data a multi-chip
    run produces (pad fraction, per-device members, all-gather bytes from
    the meshprof layout card) lands in the bench-history payload the gate
    consumes — the multichip trajectory carries locality, not just
    throughput."""
    if jax.device_count() < 8:
        pytest.skip("needs >= 8 devices (virtual CPU mesh)")
    import importlib.util
    import os

    import jax.numpy as jnp

    from ai_crypto_trader_tpu.backtest.strategy import _HIGHS, _LOWS
    from ai_crypto_trader_tpu.config import GAParams
    from ai_crypto_trader_tpu.evolve import run_ga
    from ai_crypto_trader_tpu.parallel import MeshPartitioner, make_mesh
    from ai_crypto_trader_tpu.utils import meshprof

    def fitness(p):                       # fresh closure → fresh program
        g = jnp.stack(list(p))
        span = jnp.asarray(_HIGHS - _LOWS, jnp.float32)
        return -jnp.sum((g / span) ** 2)

    mesh = make_mesh(data_parallel=8, model_parallel=1)
    mp = meshprof.MeshProf()
    cfg = GAParams(population_size=10, generations=2, elite_size=2)
    with meshprof.use(mp):
        run_ga(jax.random.PRNGKey(2), fitness, cfg,
               partitioner=MeshPartitioner(mesh))
    layout = mp.layouts["ga_scan"]
    assert layout.devices == 8 and len(layout.device_names) == 8

    # the exact stamping path bench_ga uses, against a private history
    spec = importlib.util.spec_from_file_location(
        "bench_mc_test", os.path.join(os.path.dirname(__file__), "..",
                                      "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    row = {"metric": "ga_backtests_per_sec", "value": 123.0,
           "unit": "backtests/s", "device_kind": "cpu",
           "devices": layout.devices,
           "pad_fraction": round(layout.pad_fraction, 4),
           "members_per_device": layout.members_per_device,
           "collective_bytes": layout.collective_bytes}
    hist = tmp_path / "hist.jsonl"
    bench.append_history([row], path=str(hist))
    rows = bench.load_history(str(hist))
    assert len(rows) == 1
    rec = rows[0]
    assert rec["devices"] == 8
    assert rec["pad_fraction"] == 0.375          # pop 10 on 8 devices
    assert rec["members_per_device"] == 2.0
    assert rec["collective_bytes"] > 0
    # the gate keys the sharded trajectory apart from 1-chip rows
    # (key layout: metric, device_kind, scale, devices, mode,
    # tenants_cap, aot_cache, dynamics)
    assert bench._gate_key(rec)[3] == 8


def test_sharded_pbt_mesh_matches_single_device(ohlcv, mesh8):
    """ISSUE 19 slow satellite: the PBT generation program sharded over
    an 8-device mesh reproduces the single-device fleet BIT-FOR-BIT (the
    collective only all-gathers per-member results), and a ragged fleet
    pins its pad fraction on the ``pbt_generation`` layout card — 10
    members over 8 devices pad by 6 (fraction 0.375), the analytic
    ``Partitioner.pad_for`` twin agreeing."""
    if jax.device_count() < 8:
        pytest.skip("needs >= 8 devices (virtual CPU mesh)")
    import numpy as np
    import jax.numpy as jnp

    from ai_crypto_trader_tpu import ops
    from ai_crypto_trader_tpu.parallel import MeshPartitioner
    from ai_crypto_trader_tpu.rl import DQNConfig, make_env_params
    from ai_crypto_trader_tpu.rl.population import PBTConfig, train_pbt
    from ai_crypto_trader_tpu.utils import meshprof

    key = jax.random.PRNGKey(9)
    arrays = {k: jnp.asarray(v[:256]) for k, v in ohlcv.items()
              if k != "regime"}
    env = make_env_params(ops.compute_indicators(arrays), episode_len=32)
    cfg = DQNConfig(num_envs=2, rollout_len=2, hidden=(8,),
                    replay_capacity=64, batch_size=8,
                    learn_steps_per_iter=1)
    pcfg = PBTConfig(population=16, generations=2,
                     iters_per_generation=2, eval_steps=4)

    res_single = train_pbt(key, env, cfg, pcfg)
    res_mesh = train_pbt(key, env, cfg, pcfg,
                         partitioner=MeshPartitioner(mesh8))
    np.testing.assert_array_equal(res_mesh.fitness, res_single.fitness)
    for a, b in zip(jax.tree.leaves(res_mesh.state),
                    jax.tree.leaves(res_single.state)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for hm, hs in zip(res_mesh.history, res_single.history):
        assert hm["lineage"] == hs["lineage"]
        assert hm["best_fitness"] == hs["best_fitness"]

    # ragged fleet: pad-fraction pinned on the trace-time layout card
    part = MeshPartitioner(mesh8)
    assert part.pad_for(10) == 6
    mp_obs = meshprof.MeshProf()
    pcfg10 = PBTConfig(population=10, generations=1,
                       iters_per_generation=1, eval_steps=2)
    with meshprof.use(mp_obs):
        train_pbt(key, env, cfg, pcfg10, partitioner=part)
    layout = mp_obs.layouts["pbt_generation"]
    assert layout.devices == 8
    assert layout.pad_fraction == 0.375
    assert layout.members_per_device == 2.0
