"""The multichip dryrun as a pytest (ISSUE 11 CI satellite).

`__graft_entry__.dryrun_multichip` exercises the four sharded programs on
an n-device mesh — dp×tp transformer training step, the Partitioner-
routed GA population sweep, the sequence-parallel scan, and ring-attention
training — and was previously only runnable by the driver (the
MULTICHIP_r0*.json artifacts).  Promoted to the slow tier so the sharded
paths rot loudly; skips cleanly when fewer than 8 devices are visible
(conftest.py forces 8 virtual CPU devices, so the skip only fires outside
the test harness)."""

import jax
import pytest

pytestmark = pytest.mark.slow


def test_dryrun_multichip_8devices(capsys):
    if jax.device_count() < 8:
        pytest.skip("needs >= 8 devices (virtual CPU mesh)")
    import __graft_entry__

    __graft_entry__.dryrun_multichip(8)
    out = capsys.readouterr().out
    assert "dryrun_multichip(8) OK" in out


def test_sharded_ga_locality_lands_in_bench_history(tmp_path):
    """ISSUE 12 CI satellite: the per-device locality data a multi-chip
    run produces (pad fraction, per-device members, all-gather bytes from
    the meshprof layout card) lands in the bench-history payload the gate
    consumes — the multichip trajectory carries locality, not just
    throughput."""
    if jax.device_count() < 8:
        pytest.skip("needs >= 8 devices (virtual CPU mesh)")
    import importlib.util
    import os

    import jax.numpy as jnp

    from ai_crypto_trader_tpu.backtest.strategy import _HIGHS, _LOWS
    from ai_crypto_trader_tpu.config import GAParams
    from ai_crypto_trader_tpu.evolve import run_ga
    from ai_crypto_trader_tpu.parallel import MeshPartitioner, make_mesh
    from ai_crypto_trader_tpu.utils import meshprof

    def fitness(p):                       # fresh closure → fresh program
        g = jnp.stack(list(p))
        span = jnp.asarray(_HIGHS - _LOWS, jnp.float32)
        return -jnp.sum((g / span) ** 2)

    mesh = make_mesh(data_parallel=8, model_parallel=1)
    mp = meshprof.MeshProf()
    cfg = GAParams(population_size=10, generations=2, elite_size=2)
    with meshprof.use(mp):
        run_ga(jax.random.PRNGKey(2), fitness, cfg,
               partitioner=MeshPartitioner(mesh))
    layout = mp.layouts["ga_scan"]
    assert layout.devices == 8 and len(layout.device_names) == 8

    # the exact stamping path bench_ga uses, against a private history
    spec = importlib.util.spec_from_file_location(
        "bench_mc_test", os.path.join(os.path.dirname(__file__), "..",
                                      "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    row = {"metric": "ga_backtests_per_sec", "value": 123.0,
           "unit": "backtests/s", "device_kind": "cpu",
           "devices": layout.devices,
           "pad_fraction": round(layout.pad_fraction, 4),
           "members_per_device": layout.members_per_device,
           "collective_bytes": layout.collective_bytes}
    hist = tmp_path / "hist.jsonl"
    bench.append_history([row], path=str(hist))
    rows = bench.load_history(str(hist))
    assert len(rows) == 1
    rec = rows[0]
    assert rec["devices"] == 8
    assert rec["pad_fraction"] == 0.375          # pop 10 on 8 devices
    assert rec["members_per_device"] == 2.0
    assert rec["collective_bytes"] > 0
    # the gate keys the sharded trajectory apart from 1-chip rows
    assert bench._gate_key(rec)[-1] == 8
