"""Decision provenance & model-quality observatory (obs/).

Covers the flight recorder (write → checksummed replay → query,
gate vocabulary, provenance chain), prediction outcome resolution
against a scripted candle future, on-device drift detection (PSI out of
the fused tick dispatch, host/device parity, alert coherence extending
the PR 1 suite), PnL attribution folding, the metrics cardinality
guard, and the scorecard-gated HPO adoption path.
"""

import asyncio
import json
import os

import numpy as np
import pytest

from ai_crypto_trader_tpu.obs.attribution import PnLAttribution
from ai_crypto_trader_tpu.obs.drift import (
    DRIFT_FEATURES,
    N_BINS,
    feature_names,
    psi,
    reference_histogram,
)
from ai_crypto_trader_tpu.obs.flightrec import (
    GATES,
    FlightRecorder,
    format_why,
    load_decisions,
)
from ai_crypto_trader_tpu.obs.scorecard import Scorecard
from ai_crypto_trader_tpu.shell.bus import EventBus
from ai_crypto_trader_tpu.utils.metrics import MetricsRegistry

REPO = os.path.join(os.path.dirname(__file__), "..")


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------

class TestFlightRecorder:
    def test_round_trip_write_replay_query(self, tmp_path):
        """Write vetoed + executed + closed decisions, replay the
        checksummed JSONL, and query the joined records — the full
        signal→order→fill→PnL chain survives the file."""
        path = str(tmp_path / "dec.jsonl")
        fr = FlightRecorder(path=path, now_fn=lambda: 1000.0)
        # vetoed decision
        v = fr.begin("BTCUSDC", features={"signal": "BUY"})
        fr.set_verdict(v, {"decision": "BUY", "confidence": 0.3},
                       explanation={"narrative": "weak setup",
                                    "supporting_factors": ["rsi"]})
        fr.veto(v, "confidence_floor", detail="0.30 < 0.70")
        # executed + closed decision
        e = fr.begin("BTCUSDC", features={"signal": "BUY"})
        fr.set_verdict(e, {"decision": "BUY", "confidence": 0.9})
        fr.execution(e, "wj-ent-BTCUSDC-1", symbol="BTCUSDC", quantity=0.5)
        fr.fill("wj-ent-BTCUSDC-1", 42_000.0, 0.5, symbol="BTCUSDC")
        fr.closure("wj-ent-BTCUSDC-1", "BTCUSDC", 43_000.0, 500.0,
                   "Take Profit")
        fr.close()

        records, stats = load_decisions(path)
        assert stats["replayed"] >= 4 and stats["corrupt_records"] == 0
        assert len(records) == 2
        vetoed = next(r for r in records if r["status"] == "vetoed")
        assert vetoed["gate"] == "confidence_floor"
        assert vetoed["gate_detail"] == "0.30 < 0.70"
        assert vetoed["explanation"]["narrative"] == "weak setup"
        closed = next(r for r in records if r["status"] == "closed")
        assert closed["exec"]["client_order_id"] == "wj-ent-BTCUSDC-1"
        assert closed["fills"][0]["price"] == 42_000.0
        assert closed["closure"]["pnl"] == 500.0
        assert closed["trace_id"]

        # in-memory query mirrors the file
        hits = fr.query(symbol="BTCUSDC")
        assert len(hits) == 2
        by_trace = fr.query(trace_id=hits[0]["trace_id"])
        assert by_trace and by_trace[0]["id"] == hits[0]["id"]
        why = fr.why("BTCUSDC")
        assert any("VETO [confidence_floor]" in line for line in why)
        assert any("Take Profit" in line for line in why)

    def test_corrupt_line_skipped_not_trusted(self, tmp_path):
        path = str(tmp_path / "dec.jsonl")
        fr = FlightRecorder(path=path)
        for i in range(3):
            fr.veto(fr.begin("ETHUSDC"), "not_buy")
        fr.close()
        lines = open(path).read().splitlines()
        # bit-rot the middle record; append a torn tail
        lines[1] = lines[1][:-10] + '"corrupted"'
        open(path, "w").write("\n".join(lines) + "\n" + lines[0][:17])
        records, stats = load_decisions(path)
        assert stats["corrupt_records"] == 1 and stats["torn_tail"]
        assert len(records) == 2

    def test_throttle_hits_counted_not_recorded(self, tmp_path):
        """analysis_interval fires per symbol per POLL: it is a counter
        (rate series + why() summary), never a ring slot or JSONL record
        — real decisions own both."""
        m = MetricsRegistry()
        path = str(tmp_path / "dec.jsonl")
        fr = FlightRecorder(path=path, metrics=m)
        for _ in range(5):
            fr.throttled("BTCUSDC")
        fr.veto(fr.begin("BTCUSDC"), "strength_floor")
        fr.close()
        records, _ = load_decisions(path)
        assert [r["gate"] for r in records] == ["strength_floor"]
        assert len(fr.query(symbol="BTCUSDC", limit=0)) == 1
        assert fr.status()["throttled"] == 5
        key = m._key("decision_vetoes_total", {"gate": "analysis_interval"})
        assert m.counters[key] == 5.0
        assert any("5 polls throttled" in line for line in fr.why("BTCUSDC"))

    def test_execution_supersedes_quarantine_veto(self):
        """A decision parked by mark_open('quarantine') that the executor
        later drains must not keep the provisional gate — an executed
        record never carries one, in the ring OR through replay."""
        fr = FlightRecorder()
        rid = fr.begin("BTCUSDC")
        fr.mark_open("quarantine")
        assert fr.query(symbol="BTCUSDC")[0]["status"] == "vetoed"
        fr.execution(rid, "wj-ent-BTCUSDC-3", symbol="BTCUSDC")
        rec = fr.query(symbol="BTCUSDC")[0]
        assert rec["status"] == "executed"
        assert rec["gate"] is None and rec["gate_detail"] is None
        assert fr.vetoed == 0

    def test_quarantine_then_execution_replay_clears_gate(self, tmp_path):
        path = str(tmp_path / "dec.jsonl")
        fr = FlightRecorder(path=path)
        rid = fr.begin("ETHUSDC", features={"signal": "BUY"})
        fr.veto(rid, "quarantine")           # journaled provisional veto
        fr.execution(rid, "wj-ent-ETHUSDC-1", symbol="ETHUSDC")
        fr.close()
        records, _ = load_decisions(path)
        assert len(records) == 1
        assert records[0]["status"] == "executed"
        assert records[0]["gate"] is None
        assert records[0]["features"] == {"signal": "BUY"}

    def test_synthetic_veto_does_not_clobber_executed_record(self, tmp_path):
        """Crash-in-placement-window twin: the execution journaled (flush
        before place_order), the process died, and AFTER restart — ring
        lost — recovery resolves the intent as never-placed and vetoes by
        decision_id.  Replay must show the veto while keeping the original
        record's features, exec and trace."""
        path = str(tmp_path / "dec.jsonl")
        fr = FlightRecorder(path=path)
        rid = fr.begin("BTCUSDC", features={"signal": "BUY"})
        fr.execution(rid, "wj-ent-BTCUSDC-7", symbol="BTCUSDC")
        trace = fr.query(symbol="BTCUSDC")[0]["trace_id"]
        fr.close()
        fr2 = FlightRecorder(path=path)          # restart: empty ring
        fr2.veto(rid, "entry_rejected", symbol="BTCUSDC",
                 detail="intent discarded: order never reached the venue")
        fr2.close()
        records, _ = load_decisions(path)
        assert len(records) == 1
        rec = records[0]
        assert rec["status"] == "vetoed"
        assert rec["gate"] == "entry_rejected"
        assert rec["features"] == {"signal": "BUY"}       # not clobbered
        assert rec["exec"]["client_order_id"] == "wj-ent-BTCUSDC-7"
        assert rec["trace_id"] == trace

    def test_outcome_veto_journal_record_carries_verdict(self, tmp_path):
        """The outcome-probability veto is terminal (journals the record):
        it must land AFTER set_verdict so the durable copy matches the
        ring — verdict and explanation included."""
        from ai_crypto_trader_tpu.shell.analyzer import SignalAnalyzer

        class BullTrader:
            async def analyze_trade_opportunity(self, ctx):
                return {"decision": "BUY", "confidence": 0.9,
                        "reasoning": "test", "model_version": "t1"}

        class Pessimist:
            def predict_trade_outcome(self, feats):
                return {"status": "success", "success_probability": 0.05}

        path = str(tmp_path / "dec.jsonl")
        fr = FlightRecorder(path=path)
        an = SignalAnalyzer(EventBus(), now_fn=lambda: 1_000.0,
                            flightrec=fr, trader=BullTrader(),
                            outcome_model=Pessimist())
        signal = asyncio.run(an.handle_update({
            "symbol": "BTCUSDC", "current_price": 100.0, "signal": "BUY",
            "signal_strength": 80.0, "volatility": 0.01,
            "avg_volume": 1000.0, "rsi": 25.0}))
        assert signal["decision"] == "HOLD"      # downgraded by the gate
        fr.close()
        records, _ = load_decisions(path)
        rec = next(r for r in records if r["gate"] == "outcome_probability")
        assert rec["verdict"]["decision"] == "HOLD"
        assert rec["explanation"]["narrative"]

    def test_not_placed_recovery_vetoes_flight_record(self, tmp_path):
        """Executor integration for the crash-window discard: a pending
        entry intent whose order never reached the venue finalizes its
        decision record as a veto at resolution time."""
        from ai_crypto_trader_tpu.config import TradingParams
        from ai_crypto_trader_tpu.data.ingest import from_dict
        from ai_crypto_trader_tpu.data.synthetic import generate_ohlcv
        from ai_crypto_trader_tpu.shell.exchange import FakeExchange
        from ai_crypto_trader_tpu.shell.executor import TradeExecutor

        series = from_dict(generate_ohlcv(n=300, seed=5), symbol="BTCUSDC")
        ex = FakeExchange({"BTCUSDC": series}, quote_balance=10_000)
        ex.advance(steps=64)
        path = str(tmp_path / "dec.jsonl")
        fr = FlightRecorder(path=path)
        exe = TradeExecutor(EventBus(), ex, trading=TradingParams(),
                            flightrec=fr)
        rid = fr.begin("BTCUSDC")
        fr.execution(rid, "wj-ent-BTCUSDC-1", symbol="BTCUSDC")
        exe.pending_intents["wj-ent-BTCUSDC-1"] = {
            "phase": "entry", "symbol": "BTCUSDC",
            "client_order_id": "wj-ent-BTCUSDC-1", "quantity": 0.1,
            "sl_pct": 2.0, "tp_pct": 4.0,
            "source": {"decision_id": rid, "family": "rsi_macd"}}
        report = asyncio.run(exe.resolve_pending_intents())
        assert report["discarded"] == 1
        fr.close()
        records, _ = load_decisions(path)
        rec = next(r for r in records if r["id"] == rid)
        assert rec["status"] == "vetoed"
        assert rec["gate"] == "entry_rejected"

    def test_first_gate_wins(self):
        fr = FlightRecorder()
        rid = fr.begin("BTCUSDC")
        fr.veto(rid, "outcome_probability")
        fr.veto(rid, "not_buy")             # executor's later, blunter gate
        rec = fr.query(symbol="BTCUSDC")[0]
        assert rec["gate"] == "outcome_probability"

    def test_ring_bounded_and_coid_index_pruned(self):
        fr = FlightRecorder(ring_size=8)
        for i in range(20):
            rid = fr.begin("BTCUSDC")
            fr.execution(rid, f"wj-ent-BTCUSDC-{i}")
        assert len(fr.query(limit=0)) == 8
        assert len(fr._by_coid) == 8        # evicted entries release index

    def test_veto_metrics_use_known_gates(self):
        m = MetricsRegistry()
        fr = FlightRecorder(metrics=m)
        fr.veto(fr.begin("BTCUSDC"), "pending_intent")
        key = [k for k in m.counters if "decision_vetoes_total" in k]
        assert key and 'gate="pending_intent"' in key[0]
        assert "pending_intent" in GATES


class TestExecutorGateVocabulary:
    def test_veto_reason_covers_every_should_execute_path(self):
        """veto_reason is the single source behind should_execute: each
        rejecting configuration returns a gate from the documented
        vocabulary, and None ⇔ executable."""
        from ai_crypto_trader_tpu.config import TradingParams
        from ai_crypto_trader_tpu.shell.executor import TradeExecutor
        from ai_crypto_trader_tpu.shell.exchange import FakeExchange
        from ai_crypto_trader_tpu.data.ingest import from_dict
        from ai_crypto_trader_tpu.data.synthetic import generate_ohlcv

        series = from_dict(generate_ohlcv(n=300, seed=1), symbol="BTCUSDC")
        ex = FakeExchange({"BTCUSDC": series}, quote_balance=10_000)
        exe = TradeExecutor(EventBus(), ex, trading=TradingParams(
            ai_confidence_threshold=0.7, min_signal_strength=70.0,
            max_positions=1))
        good = {"symbol": "BTCUSDC", "signal": "BUY", "decision": "BUY",
                "confidence": 0.9, "signal_strength": 80.0,
                "current_price": 100.0, "volatility": 0.01,
                "avg_volume": 1000.0}
        assert exe.veto_reason(good) is None
        assert exe.should_execute(good)
        cases = [
            ({"current_price": float("nan")}, "nan_gate"),
            ({"current_price": 0.0}, "nan_gate"),
            ({"volatility": float("inf")}, "nan_gate"),
            ({"confidence": 0.2}, "confidence_floor"),
            ({"signal_strength": 10.0}, "strength_floor"),
            ({"decision": "HOLD", "signal": "HOLD"}, "not_buy"),
            ({"signal": "NEUTRAL"}, "signal_disagreement"),
        ]
        for patch, gate in cases:
            sig = {**good, **patch}
            assert exe.veto_reason(sig) == gate, (patch, gate)
            assert not exe.should_execute(sig)
            assert gate in GATES
        exe.pending_intents["c1"] = {"symbol": "BTCUSDC"}
        assert exe.veto_reason(good) == "pending_intent"
        exe.pending_intents.clear()


# ---------------------------------------------------------------------------
# scorecard: outcome resolution against a scripted candle future
# ---------------------------------------------------------------------------

def _kline(ts_ms, close):
    return [ts_ms, close, close, close, close, 10.0]


class TestScorecardResolution:
    def _card(self, bus):
        return Scorecard(bus=bus, min_samples=2, hit_tolerance=0.01)

    def test_resolution_against_scripted_future(self):
        """Two predictions: one directionally correct & within tolerance,
        one wrong — accuracy 0.5, hit-rate 0.5, Brier from confidences."""
        bus = EventBus()
        sc = self._card(bus)
        base = 1_000_000
        # prediction 1: up from 100 → realized 101 (correct, hit at 1%)
        sc.record_prediction({
            "symbol": "BTCUSDC", "interval": "1m", "model_type": "lstm",
            "predicted_price": 101.0, "confidence": 0.8,
            "reference_ts": base, "horizon_s": 60.0,
            "reference_price": 100.0})
        # prediction 2 (later ref): up from 101 → realized 95 (wrong)
        sc.record_prediction({
            "symbol": "BTCUSDC", "interval": "1m", "model_type": "lstm",
            "predicted_price": 103.0, "confidence": 0.9,
            "reference_ts": base + 60_000, "horizon_s": 60.0,
            "reference_price": 101.0})
        # nothing resolves before the horizon candle exists
        bus.set("historical_data_BTCUSDC_1m", [_kline(base, 100.0)])
        assert sc.resolve_due() == 0
        # prediction 2's horizon candle is the NEWEST row → possibly still
        # forming on a live venue, so it must NOT resolve yet
        bus.set("historical_data_BTCUSDC_1m", [
            _kline(base, 100.0), _kline(base + 60_000, 101.0),
            _kline(base + 120_000, 95.0)])
        assert sc.resolve_due() == 1
        # the next candle arriving proves it closed → resolves at 95
        bus.set("historical_data_BTCUSDC_1m", [
            _kline(base, 100.0), _kline(base + 60_000, 101.0),
            _kline(base + 120_000, 95.0), _kline(base + 180_000, 96.0)])
        assert sc.resolve_due() == 1
        score = sc.scores()[("lstm", "BTCUSDC", "1m")]
        assert score["n"] == 2 and score["live"]
        assert score["directional_accuracy"] == pytest.approx(0.5)
        assert score["hit_rate"] == pytest.approx(0.5)
        # Brier: correct@0.8 → 0.04; wrong@0.9 → 0.81 → mean 0.425
        assert score["brier"] == pytest.approx((0.04 + 0.81) / 2)
        assert sc.alert_state()["model_brier_worst"] == pytest.approx(0.425)
        assert sc.alert_state()["model_accuracy_worst"] == pytest.approx(0.5)

    def test_same_forecast_not_double_registered(self):
        bus = EventBus()
        sc = self._card(bus)
        p = {"symbol": "BTCUSDC", "interval": "1m", "model_type": "gru",
             "predicted_price": 1.0, "confidence": 0.5,
             "reference_ts": 5_000, "horizon_s": 60.0,
             "reference_price": 1.0}
        assert sc.record_prediction(p)
        assert not sc.record_prediction(p)     # idempotent per reference_ts
        assert len(sc._pending) == 1

    def test_legacy_payload_without_provenance_ignored(self):
        sc = self._card(EventBus())
        assert not sc.record_prediction({
            "symbol": "BTCUSDC", "interval": "1m",
            "predicted_price": 1.0, "confidence": 0.5})

    def test_unresolvable_prediction_expires(self):
        bus = EventBus()
        sc = self._card(bus)
        sc.expire_horizons = 2.0
        sc.record_prediction({
            "symbol": "BTCUSDC", "interval": "1m", "model_type": "lstm",
            "predicted_price": 1.0, "confidence": 0.5,
            "reference_ts": 0, "horizon_s": 60.0, "reference_price": 1.0})
        # venue gap: candles jump far past the horizon with none at it
        bus.set("historical_data_BTCUSDC_1m", [_kline(-60_000, 1.0)])
        sc.resolve_due()
        assert len(sc._pending) == 1           # not yet expired
        # the window only ever holds candles BEFORE the horizon, but time
        # moved far past it → expire
        bus.set("historical_data_BTCUSDC_1m",
                [_kline(-60_000, 1.0), _kline(-1, 1.0)])
        sc.expire_horizons = -1.0              # force the expiry branch
        sc.resolve_due()
        assert len(sc._pending) == 0 and sc.expired_total == 1

    def test_adoption_gate(self):
        sc = Scorecard(min_samples=2)
        for correct in (True, True, True, False):   # lstm: 0.75
            sc._score({"symbol": "B", "interval": "1m",
                       "model_type": "lstm", "reference_price": 100.0,
                       "predicted_price": 101.0, "confidence": 0.5},
                      101.0 if correct else 99.0)
        for correct in (True, False, False, False):  # gru: 0.25
            sc._score({"symbol": "B", "interval": "1m",
                       "model_type": "gru", "reference_price": 100.0,
                       "predicted_price": 101.0, "confidence": 0.5},
                      101.0 if correct else 99.0)
        ok, why = sc.adoption_gate("gru", "lstm", "B", "1m")
        assert not ok and "live score" in why
        ok, why = sc.adoption_gate("lstm", "gru", "B", "1m")
        assert ok and why == "candidate_better"
        ok, why = sc.adoption_gate("tcn", "lstm", "B", "1m")
        assert ok and why == "candidate_unscored"
        ok, why = sc.adoption_gate("lstm", "lstm", "B", "1m")
        assert ok and why == "same_architecture"

    def test_hpo_adoption_blocked_by_scorecard(self):
        """The registry/hot-swap path consults the live scorecard: an HPO
        winner with a known-worse live score than the incumbent is NOT
        adopted and lands in the registry as shadow."""
        from ai_crypto_trader_tpu.models.service import PredictionService
        from ai_crypto_trader_tpu.strategy.registry import ModelRegistry

        bus = EventBus()
        sc = Scorecard(bus=bus, min_samples=1)
        reg = ModelRegistry()
        svc = PredictionService(bus, ["BTCUSDC"], intervals=("1m",),
                                now_fn=lambda: 1000.0, epochs=1,
                                scorecard=sc, registry=reg,
                                hpo_trials=2, seq_len=8)

        class Incumbent:
            model_type = "lstm"

        svc.models[("BTCUSDC", "1m")] = Incumbent()
        # live scores: incumbent great, candidate terrible
        sc._score({"symbol": "BTCUSDC", "interval": "1m",
                   "model_type": "lstm", "reference_price": 100.0,
                   "predicted_price": 101.0, "confidence": 0.5}, 101.0)
        sc._score({"symbol": "BTCUSDC", "interval": "1m",
                   "model_type": "gru", "reference_price": 100.0,
                   "predicted_price": 101.0, "confidence": 0.5}, 99.0)

        import ai_crypto_trader_tpu.models.hpo as hpo_mod
        orig = hpo_mod.optimize_hyperparameters

        def fake_hpo(*a, **kw):
            return {"best_params": {"model_type": "gru", "units": 8,
                                    "dropout": 0.0, "learning_rate": 1e-3,
                                    "batch_size": 8},
                    "best_val_loss": 0.001}

        hpo_mod.optimize_hyperparameters = fake_hpo
        try:
            rec = svc._run_hpo("BTCUSDC", "1m",
                               np.ones((64, 5), np.float32), 1000.0)
        finally:
            hpo_mod.optimize_hyperparameters = orig
        assert rec["adoption"] == "blocked_by_scorecard"
        assert "live score" in rec["adoption_reason"]
        # incumbent still serving; candidate versioned as shadow
        assert svc.models[("BTCUSDC", "1m")].model_type == "lstm"
        entry = reg.entries[rec["version"]]
        assert entry["status"] == "shadow"

    def test_periodic_retrain_cannot_clobber_gated_incumbent(self):
        """The regular retrain trains the service's DEFAULT architecture;
        when that would replace a different-arch incumbent it is an
        architecture swap and must pass the same live gate — otherwise a
        blocked HPO candidate's arch sneaks in via the 24h cadence."""
        import jax

        from ai_crypto_trader_tpu.models.service import PredictionService
        from ai_crypto_trader_tpu.models.train import train_model

        bus = EventBus()
        sc = Scorecard(bus=bus, min_samples=1)
        feats = np.cumsum(np.abs(np.random.default_rng(1)
                                 .normal(1, 0.1, (96, 5))), axis=0) \
            .astype(np.float32)
        rows = [_kline(i * 60_000, float(feats[i, 3])) for i in range(96)]
        bus.set("historical_data_BTCUSDC_1m", rows)
        svc = PredictionService(bus, ["BTCUSDC"], intervals=("1m",),
                                now_fn=lambda: 1000.0, epochs=1, seq_len=8,
                                units=4, model_type="gru", scorecard=sc)
        incumbent = train_model(jax.random.PRNGKey(0), feats, "lstm",
                                seq_len=8, epochs=1, units=4, target_col=3)
        svc.models[("BTCUSDC", "1m")] = incumbent
        # live scores: lstm incumbent good, gru (the default arch) bad
        for arch, realized in (("lstm", 101.0), ("gru", 99.0)):
            sc._score({"symbol": "BTCUSDC", "interval": "1m",
                       "model_type": arch, "reference_price": 100.0,
                       "predicted_price": 101.0, "confidence": 0.5},
                      realized)
        out = svc._compute(1000.0, None)     # retrain cadence is due
        assert out["trained"] == 0
        assert svc.models[("BTCUSDC", "1m")] is incumbent
        # ... and the pair is deferred, not retried every tick
        assert svc._last_training[("BTCUSDC", "1m")] == 1000.0

    def test_prediction_payload_carries_resolution_provenance(self):
        """Satellite: the service snapshot records explicit timestamps,
        horizon and reference price — previously only the value."""
        import jax

        from ai_crypto_trader_tpu.models.service import PredictionService
        from ai_crypto_trader_tpu.models.train import train_model

        bus = EventBus()
        base = 7_000_000
        feats = np.cumsum(np.abs(np.random.default_rng(0)
                                 .normal(1, 0.1, (96, 5))), axis=0) \
            .astype(np.float32)
        rows = [_kline(base + i * 60_000, float(feats[i, 3]))
                for i in range(96)]
        bus.set("historical_data_BTCUSDC_1m", rows)
        svc = PredictionService(bus, ["BTCUSDC"], intervals=("1m",),
                                now_fn=lambda: 12_345.0, epochs=1,
                                seq_len=8, units=4, model_type="gru")
        svc.models[("BTCUSDC", "1m")] = train_model(
            jax.random.PRNGKey(0), feats, "gru", seq_len=8, epochs=1,
            units=4, target_col=3)
        asyncio.run(svc.run_once())
        p = bus.get("nn_prediction_BTCUSDC_1m")
        assert p["predicted_at"] == 12_345.0
        assert p["horizon_s"] == 60.0
        assert p["reference_ts"] == float(rows[-1][0])
        assert p["reference_price"] == pytest.approx(float(feats[-1, 3]))
        assert p["model_type"] == "gru"
        # and the scorecard can ingest it directly
        sc = Scorecard(bus=bus)
        assert sc.observe_bus() == 1


# ---------------------------------------------------------------------------
# on-device drift
# ---------------------------------------------------------------------------

LIMIT = 128


def _engine_with_window(seed=3, shift=0.0, scale=1.0):
    """A 1-symbol engine fed a full window; optional distribution shift."""
    from ai_crypto_trader_tpu.data.synthetic import generate_ohlcv
    from ai_crypto_trader_tpu.ops.tick_engine import TickEngine

    d = generate_ohlcv(n=LIMIT + 8, seed=seed)
    rows = [[i * 60_000,
             float(d["open"][i]) * scale + shift,
             float(d["high"][i]) * scale + shift,
             float(d["low"][i]) * scale + shift,
             float(d["close"][i]) * scale + shift,
             float(d["volume"][i])]
            for i in range(LIMIT)]
    eng = TickEngine(["BTCUSDC"], ("1m",), window=LIMIT)
    eng.ingest("BTCUSDC", "1m", rows)
    return eng, rows


class TestOnDeviceDrift:
    def test_reference_capture_then_stable_psi_near_zero(self):
        eng, rows = _engine_with_window()
        eng.step()
        drift = eng.last_drift
        # first step: reference captured AFTER the dispatch — not yet set
        assert not drift["ref_set"][0, 0]
        assert eng._drift_ref_set[0, 0]
        eng.ingest("BTCUSDC", "1m", rows)      # identical window
        eng.step()
        drift = eng.last_drift
        assert drift["ref_set"][0, 0]
        vals = drift["psi"][0, 0]
        assert np.isfinite(vals).all()
        assert float(np.max(np.abs(vals))) < 1e-5   # same window ⇒ no drift

    def test_shifted_distribution_raises_psi_above_alert(self):
        """Re-seed the lane with a price regime whose indicator
        distributions differ → PSI crosses the SignalDrift threshold for
        at least one feature, while the reference is retained."""
        eng, rows = _engine_with_window()
        eng.step()
        eng.ingest("BTCUSDC", "1m", rows)
        eng.step()                              # reference now live
        base_psi = eng.last_drift["psi"][0, 0].copy()
        # monotone ramp: RSI pins high, bb_position pins top — a real
        # distribution shift vs the stationary synthetic regime
        ramp = [[(LIMIT + i) * 60_000, 100.0 + i, 101.0 + i, 99.0 + i,
                 100.5 + i, 50.0] for i in range(LIMIT)]
        eng.ingest("BTCUSDC", "1m", ramp)
        eng.step()
        drift = eng.last_drift
        assert drift["ref_set"][0, 0]
        shifted = drift["psi"][0, 0]
        assert float(np.max(shifted)) > 0.25, (base_psi, shifted)

    def test_device_psi_matches_host_twin(self):
        """The in-program PSI equals obs.drift.psi over the same
        histograms — the device computation is pinned to the spec."""
        eng, rows = _engine_with_window()
        eng.step()
        eng.ingest("BTCUSDC", "1m", rows)
        eng.step()
        drift = eng.last_drift
        host = psi(drift["hist"][0, 0], eng._drift_ref_np[0, 0])
        np.testing.assert_allclose(drift["psi"][0, 0], host,
                                   rtol=1e-4, atol=1e-5)

    def test_training_time_reference_installs(self):
        eng, rows = _engine_with_window()
        ref = reference_histogram({"rsi": np.full(64, 99.0)})  # pinned high
        eng.set_drift_reference("BTCUSDC", "1m", ref)
        eng.step()
        drift = eng.last_drift
        assert drift["ref_set"][0, 0]          # set BEFORE the dispatch
        k = feature_names().index("rsi")
        # live RSI is nowhere near a point-mass at 99 → large PSI
        assert float(drift["psi"][0, 0, k]) > 0.25

    def test_monitor_exposes_drift_and_launcher_alerts(self):
        """End-to-end: fused poll → monitor.last_drift → feature_psi
        gauges + SignalDrift in-process alert."""
        from ai_crypto_trader_tpu.data.ingest import from_dict
        from ai_crypto_trader_tpu.data.synthetic import generate_ohlcv
        from ai_crypto_trader_tpu.shell.exchange import FakeExchange
        from ai_crypto_trader_tpu.shell.launcher import TradingSystem

        clock = {"t": 1_000_000.0}
        d = generate_ohlcv(n=1200, seed=3)
        series = from_dict({k: v for k, v in d.items() if k != "regime"},
                           symbol="BTCUSDC")
        ex = FakeExchange({"BTCUSDC": series}, quote_balance=10_000)
        ex.advance("BTCUSDC", steps=600)
        sys_ = TradingSystem(ex, ["BTCUSDC"], now_fn=lambda: clock["t"])

        async def go():
            for _ in range(3):
                ex.advance("BTCUSDC")
                clock["t"] += 60.0
                await sys_.tick()

        asyncio.run(go())
        assert "BTCUSDC" in sys_.monitor.last_drift
        row = sys_.monitor.last_drift["BTCUSDC"]
        assert set(row) <= set(feature_names())
        text = sys_.metrics.exposition()
        assert 'crypto_trader_tpu_feature_psi{feature="rsi"' in text
        # alert rule coherence: forcing a huge PSI fires SignalDrift
        sys_.monitor.last_drift["BTCUSDC"] = {"rsi": 1.0}
        fired = sys_.alerts.evaluate(sys_._alert_state())
        assert any(a["name"] == "SignalDrift" for a in fired)

    def test_one_dispatch_contract_preserved(self, monkeypatch):
        """Drift adds ZERO host readbacks: one step stays one host_read,
        one dispatch (the acceptance criterion's contract)."""
        from ai_crypto_trader_tpu.ops import tick_engine

        eng, rows = _engine_with_window()
        syncs = {"n": 0}
        real = tick_engine.host_read

        def counting(tree):
            syncs["n"] += 1
            return real(tree)

        monkeypatch.setattr(tick_engine, "host_read", counting)
        eng.step()
        assert syncs["n"] == 1 and eng.dispatch_count == 1


class TestAlertRuleCoherence:
    """Extends the PR 1 coherence suite: the three new alerts exist in
    BOTH rule engines (in-process + PromQL) under the same names."""

    NEW_ALERTS = ("SignalDrift", "ModelCalibrationBreach",
                  "ModelAccuracyDegraded")

    def test_in_process_rules_exist_and_fire(self):
        from ai_crypto_trader_tpu.utils.alerts import AlertManager

        mgr = AlertManager()
        names = {r.name for r in mgr.rules}
        assert set(self.NEW_ALERTS) <= names
        fired = mgr.evaluate({"feature_psi_max": 0.9,
                              "model_brier_worst": 0.9,
                              "model_accuracy_worst": 0.1})
        assert set(self.NEW_ALERTS) <= {a["name"] for a in fired}
        # and resolve when healthy
        mgr.evaluate({"feature_psi_max": 0.01, "model_brier_worst": 0.05,
                      "model_accuracy_worst": 0.8})
        assert not set(self.NEW_ALERTS) & set(mgr.active)

    def test_promql_twins_exist(self):
        import yaml

        rules = yaml.safe_load(
            open(os.path.join(REPO, "monitoring/alert_rules.yml")))
        names = {r.get("alert") for g in rules["groups"]
                 for r in g["rules"]}
        assert set(self.NEW_ALERTS) <= names
        assert "MetricCardinalityClipped" in names


# ---------------------------------------------------------------------------
# PnL attribution
# ---------------------------------------------------------------------------

class TestAttribution:
    def _rec(self, pnl, family="rsi_macd", reason="Take Profit"):
        return {"symbol": "BTCUSDC", "pnl": pnl, "reason": reason,
                "source": {"family": family, "structure_version": "v1",
                           "model_version": "heuristic-1"}}

    def test_fold_by_family_and_win_rate(self):
        m = MetricsRegistry()
        attr = PnLAttribution(metrics=m)
        cursor = attr.fold_new([self._rec(10.0), self._rec(-4.0),
                                self._rec(6.0, family="bb_stoch")], 0)
        assert cursor == 3
        fam = attr.summary("family")["family"]
        assert fam["rsi_macd"]["pnl"] == pytest.approx(6.0)
        assert fam["rsi_macd"]["trades"] == 2
        assert fam["rsi_macd"]["win_rate"] == pytest.approx(0.5)
        assert fam["bb_stoch"]["win_rate"] == 1.0
        attr.export()
        text = m.exposition()
        assert ('crypto_trader_tpu_source_realized_pnl{kind="family",'
                'source="rsi_macd"}') in text
        assert "crypto_trader_tpu_source_trades_total" in text

    def test_unattributed_closures_still_fold(self):
        attr = PnLAttribution()
        attr.fold_record({"symbol": "X", "pnl": 1.0, "reason": "Stop Loss"})
        assert attr.summary("family")["family"]["unattributed"]["trades"] == 1

    def test_closure_records_carry_provenance_live(self):
        """Executor → closure record → attribution: the family stamped on
        the signal survives to the closure and folds."""
        from ai_crypto_trader_tpu.config import TradingParams
        from ai_crypto_trader_tpu.data.ingest import from_dict
        from ai_crypto_trader_tpu.data.synthetic import generate_ohlcv
        from ai_crypto_trader_tpu.shell.exchange import FakeExchange
        from ai_crypto_trader_tpu.shell.executor import TradeExecutor

        series = from_dict(generate_ohlcv(n=400, seed=2), symbol="BTCUSDC")
        ex = FakeExchange({"BTCUSDC": series}, quote_balance=10_000,
                          fee_rate=0.0)
        ex.advance(steps=64)
        fr = FlightRecorder()
        exe = TradeExecutor(EventBus(), ex, trading=TradingParams(
            ai_confidence_threshold=0.0, min_signal_strength=0.0,
            min_trade_amount=1.0), flightrec=fr)

        async def go():
            price = ex.get_ticker("BTCUSDC")["price"]
            trade = await exe.handle_signal({
                "symbol": "BTCUSDC", "signal": "BUY", "decision": "BUY",
                "confidence": 1.0, "signal_strength": 100.0,
                "current_price": price, "volatility": 0.01,
                "avg_volume": 50_000.0, "top_family": "macd_vol",
                "structure_version": "s9", "model_version": "m2",
                "decision_id": fr.begin("BTCUSDC")})
            assert trade is not None
            assert trade.source["family"] == "macd_vol"
            await exe.close_trade("BTCUSDC",
                                  ex.get_ticker("BTCUSDC")["price"], "Test")

        asyncio.run(go())
        rec = exe.closed_trades[-1]
        assert rec["source"]["family"] == "macd_vol"
        assert rec["entry_coid"].startswith("wj-ent-")
        # the flight recorder chained the closure onto the decision
        d = fr.query(symbol="BTCUSDC", limit=1)[0]
        assert d["status"] == "closed" and d["closure"]["reason"] == "Test"
        attr = PnLAttribution()
        attr.fold_new(exe.closed_trades, 0)
        assert "macd_vol" in attr.summary("family")["family"]
        assert attr.summary("structure")["structure"]["s9"]["trades"] == 1


# ---------------------------------------------------------------------------
# metrics cardinality guard
# ---------------------------------------------------------------------------

class TestCardinalityGuard:
    def test_cap_drops_new_series_and_counts(self):
        m = MetricsRegistry(max_series_per_metric=4)
        for i in range(10):
            m.set_gauge("model_hit_rate", 0.5, symbol=f"S{i}")
        kept = [k for k in m.gauges if "model_hit_rate" in k]
        assert len(kept) == 4
        dropped = [v for k, v in m.counters.items()
                   if "metric_cardinality_dropped_total" in k
                   and 'metric="model_hit_rate"' in k]
        assert dropped == [6.0]

    def test_existing_series_keep_updating_past_cap(self):
        m = MetricsRegistry(max_series_per_metric=2)
        m.inc("errors_total", kind="a")
        m.inc("errors_total", kind="b")
        m.inc("errors_total", kind="c")       # dropped
        m.inc("errors_total", kind="a")       # still counts
        assert m.counters[m._key("errors_total", {"kind": "a"})] == 2.0
        assert m._key("errors_total", {"kind": "c"}) not in m.counters

    def test_histograms_guarded_and_drop_counter_exposed(self):
        m = MetricsRegistry(max_series_per_metric=1)
        m.observe("lat_seconds", 0.1, stage="a")
        m.observe("lat_seconds", 0.1, stage="b")
        text = m.exposition()
        assert 'stage="b"' not in text
        assert ("crypto_trader_tpu_metric_cardinality_dropped_total"
                '{metric="lat_seconds"} 1.0') in text

    def test_default_cap_far_above_normal_usage(self):
        assert MetricsRegistry().max_series_per_metric >= 256


# ---------------------------------------------------------------------------
# endpoint + explain wiring
# ---------------------------------------------------------------------------

class TestDecisionsEndpoint:
    def test_dashboard_serves_decisions(self):
        import urllib.request

        from ai_crypto_trader_tpu.data.ingest import from_dict
        from ai_crypto_trader_tpu.data.synthetic import generate_ohlcv
        from ai_crypto_trader_tpu.shell.dashboard_server import DashboardServer
        from ai_crypto_trader_tpu.shell.exchange import FakeExchange
        from ai_crypto_trader_tpu.shell.launcher import TradingSystem

        clock = {"t": 1_000.0}
        series = from_dict(generate_ohlcv(n=900, seed=4), symbol="BTCUSDC")
        ex = FakeExchange({"BTCUSDC": series}, quote_balance=10_000)
        ex.advance("BTCUSDC", steps=600)
        system = TradingSystem(ex, ["BTCUSDC"], now_fn=lambda: clock["t"])
        server = DashboardServer(system, port=0).start()
        try:
            async def go():
                for _ in range(2):
                    ex.advance("BTCUSDC")
                    clock["t"] += 120.0
                    await system.tick()

            asyncio.run(go())
            url = (f"http://127.0.0.1:{server.port}/decisions"
                   f"?symbol=BTCUSDC&limit=5")
            rows = json.loads(urllib.request.urlopen(url, timeout=10).read())
            assert rows and rows[0]["symbol"] == "BTCUSDC"
            assert rows[0]["status"] in ("vetoed", "executed", "closed",
                                         "open")
            # trace filter round-trips
            tid = rows[0]["trace_id"]
            url2 = (f"http://127.0.0.1:{server.port}/decisions"
                    f"?trace_id={tid}")
            rows2 = json.loads(urllib.request.urlopen(url2,
                                                      timeout=10).read())
            assert rows2 and all(r["trace_id"] == tid for r in rows2)
            # explanation (strategy/explain.py) rode the decision record
            analyzed = [r for r in rows if r.get("explanation")]
            assert analyzed, "no decision carried an explanation"
            assert analyzed[0]["explanation"]["narrative"]
        finally:
            server.stop()
            system.shutdown()

    def test_explanation_factors_use_real_market_values(self):
        """Satellite: explain_signal now sees the update's indicator
        values (rsi/stoch/trend), not bare-signal defaults."""
        from ai_crypto_trader_tpu.shell.analyzer import SignalAnalyzer

        bus = EventBus()
        fr = FlightRecorder()
        an = SignalAnalyzer(bus, now_fn=lambda: 10_000.0, flightrec=fr)

        async def go():
            return await an.handle_update({
                "symbol": "BTCUSDC", "current_price": 100.0,
                "signal": "BUY", "signal_strength": 80.0,
                "volatility": 0.01, "avg_volume": 500_000.0,
                "rsi": 22.5, "stoch_k": 11.0, "macd": 1.5,
                "trend": "uptrend", "trend_strength": 3.0,
                "top_family": "rsi_stoch"})

        signal = asyncio.run(go())
        assert signal is not None
        assert signal["top_family"] == "rsi_stoch"
        expl = bus.get("explanation_BTCUSDC")
        assert expl["factors"]["rsi"]["value"] == 22.5
        assert expl["factors"]["rsi"]["reading"] == "oversold"
        rec = fr.query(symbol="BTCUSDC", limit=1)[0]
        assert rec["verdict"]["decision"] == signal["decision"]
        assert "rsi" in (rec["explanation"]["narrative"] or "")
        assert rec["features"]["top_family"] == "rsi_stoch"
