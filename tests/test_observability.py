"""Observability completion: structured logs, metric series, stack configs.

Covers utils/structlog.py (JSON-lines records, rotation, child loggers),
the launcher's Grafana-facing metric series, and coherence of the shipped
monitoring stack configs (Grafana provisioning panels query series the
code actually emits; compose mounts files that exist).
"""

import asyncio
import json
import os

import numpy as np
import pytest

from ai_crypto_trader_tpu.utils.structlog import StructuredLogger

REPO = os.path.join(os.path.dirname(__file__), "..")


class TestStructuredLogger:
    def test_json_lines_with_fields(self, tmp_path):
        path = str(tmp_path / "svc.jsonl")
        log = StructuredLogger("monitor", path=path, now_fn=lambda: 123.0)
        log.info("poll complete", symbols=2, latency_ms=4.5)
        log.error("boom", kind="exchange")
        rows = [json.loads(line) for line in open(path)]
        assert rows[0] == {"ts": 123.0, "level": "info", "service": "monitor",
                           "msg": "poll complete", "symbols": 2,
                           "latency_ms": 4.5}
        assert rows[1]["level"] == "error" and rows[1]["kind"] == "exchange"

    def test_min_level_filters(self, tmp_path):
        path = str(tmp_path / "svc.jsonl")
        log = StructuredLogger("x", path=path, min_level="warning")
        log.info("dropped")
        log.warning("kept")
        rows = [json.loads(line) for line in open(path)]
        assert [r["msg"] for r in rows] == ["kept"]

    def test_rotation(self, tmp_path):
        path = str(tmp_path / "svc.jsonl")
        log = StructuredLogger("x", path=path, max_bytes=500, backup_count=2)
        for i in range(100):
            log.info("filler message to push the file over the limit", i=i)
        assert os.path.exists(path + ".1")
        assert os.path.getsize(path) < 500 + 200   # fresh file after rotate

    def test_child_shares_sink_with_own_service(self, tmp_path):
        path = str(tmp_path / "svc.jsonl")
        log = StructuredLogger("launcher", path=path)
        log.child("executor").info("filled")
        rows = [json.loads(line) for line in open(path)]
        assert rows[0]["service"] == "executor"


class TestStructuredLoggerSafety:
    def test_non_serializable_fields_fall_back_to_repr(self, tmp_path):
        """A bad field value must never raise mid-hot-path (the log call
        sits inside the trading loop): objects fall back to str()/repr()."""
        class Unserializable:
            def __str__(self):
                raise RuntimeError("str() is broken too")

        path = str(tmp_path / "svc.jsonl")
        log = StructuredLogger("svc", path=path, now_fn=lambda: 1.0)
        log.info("object field", obj=Unserializable(), fine=1)
        circular = {}
        circular["self"] = circular
        log.info("circular field", loop=circular)
        rows = [json.loads(line) for line in open(path)]
        assert rows[0]["fine"] == 1
        assert "Unserializable" in rows[0]["obj"]      # repr fallback
        assert rows[1]["msg"] == "circular field"
        assert "loop" in rows[1]                       # degraded, not lost

    def test_ordinary_objects_stringified(self, tmp_path):
        path = str(tmp_path / "svc.jsonl")
        log = StructuredLogger("svc", path=path)
        log.info("set field", vals={1, 2})              # sets aren't JSON
        row = json.loads(open(path).read())
        assert "1" in row["vals"] and "2" in row["vals"]


class TestHistogramCumulativeBuckets:
    def test_buckets_monotone_cumulative_and_inf_equals_count(self):
        """Prometheus semantics: each `le` bucket includes every smaller
        bucket's observations; +Inf == _count. histogram_quantile silently
        mis-ranks on non-cumulative buckets, so this is pinned."""
        import re

        from ai_crypto_trader_tpu.utils.metrics import MetricsRegistry

        m = MetricsRegistry()
        for v in (0.0005, 0.003, 0.003, 0.07, 0.3, 2.0, 100.0):
            m.observe("lat_seconds", v, stage="x")
        text = m.exposition()
        buckets = []
        for line in text.splitlines():
            match = re.match(
                r'crypto_trader_tpu_lat_seconds_bucket\{.*le="([^"]+)"\} '
                r"(\d+)", line)
            if match:
                buckets.append((match.group(1), int(match.group(2))))
        assert [b[0] for b in buckets][-1] == "+Inf"
        counts = [b[1] for b in buckets]
        assert counts == sorted(counts), f"non-monotone buckets: {buckets}"
        # spot-check the cumulative property against the raw observations
        by_le = dict(buckets)
        assert by_le["0.001"] == 1          # 0.0005
        assert by_le["0.005"] == 3          # + 2×0.003
        assert by_le["0.1"] == 4            # + 0.07
        assert by_le["0.5"] == 5            # + 0.3
        assert by_le["5.0"] == 6            # + 2.0
        assert by_le["+Inf"] == 7           # everything
        count_line = [l for l in text.splitlines()
                      if l.startswith("crypto_trader_tpu_lat_seconds_count")][0]
        assert int(float(count_line.rsplit(" ", 1)[1])) == 7


class TestExpositionFormat:
    """Prometheus text-format fidelity: # TYPE lines, label-value
    escaping, and strict endpoint routing — a real scrape must parse
    every series, not just eyeball-friendly ones."""

    WEIRD = 'back\\slash "quoted"\nnewline'

    def _parse(self, text):
        """Minimal Prometheus text parser: {series_key: value} with label
        values UNescaped, plus the # TYPE map."""
        import re

        types, samples = {}, {}
        for line in text.splitlines():
            if line.startswith("# TYPE "):
                _, _, name, mtype = line.split(" ")
                types[name] = mtype
                continue
            m = re.match(r"([a-zA-Z_:][a-zA-Z0-9_:]*)(\{.*\})? (\S+)$", line)
            assert m, f"unparseable line: {line!r}"
            labels = {}
            if m.group(2):
                for lm in re.finditer(r'([a-zA-Z_]+)="((?:\\.|[^"\\])*)"',
                                      m.group(2)):
                    labels[lm.group(1)] = (lm.group(2)
                                           .replace("\\n", "\n")
                                           .replace('\\"', '"')
                                           .replace("\\\\", "\\"))
            samples[(m.group(1), tuple(sorted(labels.items())))] = \
                float(m.group(3))
        return types, samples

    def test_type_lines_for_every_family(self):
        from ai_crypto_trader_tpu.utils.metrics import MetricsRegistry

        m = MetricsRegistry()
        m.inc("errors_total", kind="x")
        m.set_gauge("portfolio_value_usd", 1234.5)
        m.observe("lat_seconds", 0.003)
        types, _ = self._parse(m.exposition())
        assert types["crypto_trader_tpu_errors_total"] == "counter"
        assert types["crypto_trader_tpu_portfolio_value_usd"] == "gauge"
        assert types["crypto_trader_tpu_lat_seconds"] == "histogram"

    def test_label_values_escaped_and_round_trip(self):
        """Backslash, double-quote and newline in a label value survive a
        scrape: the exposition escapes them and a parser recovers the
        original string exactly."""
        from ai_crypto_trader_tpu.utils.metrics import MetricsRegistry

        m = MetricsRegistry()
        m.inc("errors_total", kind=self.WEIRD)
        text = m.exposition()
        assert "\\\\" in text and '\\"' in text and "\\n" in text
        # escaped newline: the sample must still be ONE physical line
        sample_lines = [l for l in text.splitlines()
                        if l.startswith("crypto_trader_tpu_errors_total")]
        assert len(sample_lines) == 1
        _, samples = self._parse(text)
        key = ("crypto_trader_tpu_errors_total", (("kind", self.WEIRD),))
        assert samples[key] == 1.0

    def test_golden_histogram_parse_with_inf_bucket(self):
        from ai_crypto_trader_tpu.utils.metrics import MetricsRegistry

        m = MetricsRegistry()
        for v in (0.0005, 0.003, 0.07, 2.0):
            m.observe("lat_seconds", v, stage='s"1')
        types, samples = self._parse(m.exposition())
        assert types["crypto_trader_tpu_lat_seconds"] == "histogram"
        inf = samples[("crypto_trader_tpu_lat_seconds_bucket",
                       (("le", "+Inf"), ("stage", 's"1')))]
        count = samples[("crypto_trader_tpu_lat_seconds_count",
                         (("stage", 's"1'),))]
        assert inf == count == 4.0

    def test_serve_routes_metrics_health_404(self):
        """serve(): /metrics and /health only; anything else is 404 (it
        used to dump the exposition for every path)."""
        from ai_crypto_trader_tpu.utils.metrics import MetricsRegistry

        async def scenario():
            m = MetricsRegistry()
            m.inc("errors_total")
            srv = await m.serve("127.0.0.1", 0)
            port = srv.sockets[0].getsockname()[1]

            async def get(path):
                reader, writer = await asyncio.open_connection(
                    "127.0.0.1", port)
                writer.write(f"GET {path} HTTP/1.1\r\n\r\n".encode())
                await writer.drain()
                data = await reader.read(1 << 16)
                writer.close()
                return data.decode()

            metrics = await get("/metrics")
            health = await get("/health")
            bogus = await get("/bogus")
            root = await get("/")
            srv.close()
            await srv.wait_closed()
            return metrics, health, bogus, root

        metrics, health, bogus, root = asyncio.run(scenario())
        assert "200 OK" in metrics and "# TYPE" in metrics
        assert "200 OK" in health and "healthy" in health
        assert "404 Not Found" in bogus and "errors_total" not in bogus
        assert "404 Not Found" in root


class TestHeartbeatRegistry:
    def test_per_service_threshold_override(self):
        from ai_crypto_trader_tpu.utils.health import HeartbeatRegistry

        clock = {"t": 0.0}
        hb = HeartbeatRegistry(stale_after_s=30.0,
                               stale_after={"nn": 3600.0},
                               now_fn=lambda: clock["t"])
        hb.beat("monitor")
        hb.beat("nn")
        clock["t"] = 100.0            # past the default, inside nn's window
        assert hb.stale() == ["monitor"]
        assert hb.health() == {"monitor": False, "nn": True}
        clock["t"] = 4000.0
        assert sorted(hb.stale()) == ["monitor", "nn"]

    def test_stale_transitions_logged_once_with_service_name(self, tmp_path):
        from ai_crypto_trader_tpu.utils.health import HeartbeatRegistry

        path = str(tmp_path / "health.jsonl")
        clock = {"t": 0.0}
        hb = HeartbeatRegistry(
            stale_after_s=30.0, now_fn=lambda: clock["t"],
            log=StructuredLogger("health", path=path,
                                 now_fn=lambda: clock["t"]))
        hb.beat("monitor")
        clock["t"] = 100.0
        hb.stale()
        hb.stale()                    # steady-state: no duplicate lines
        hb.beat("monitor")            # recovery
        hb.stale()
        rows = [json.loads(line) for line in open(path)]
        assert [(r["msg"], r["service_name"]) for r in rows] == [
            ("service went stale", "monitor"),
            ("service recovered", "monitor")]
        assert rows[0]["level"] == "warning"
        assert rows[0]["threshold_s"] == 30.0


class TestLauncherMetricSeries:
    @pytest.mark.slow
    def test_dashboard_series_emitted(self):
        from ai_crypto_trader_tpu.data.ingest import from_dict
        from ai_crypto_trader_tpu.data.synthetic import generate_ohlcv
        from ai_crypto_trader_tpu.shell.exchange import FakeExchange
        from ai_crypto_trader_tpu.shell.launcher import TradingSystem

        clock = {"t": 1_000_000.0}
        d = generate_ohlcv(n=1200, seed=3)
        series = from_dict({k: v for k, v in d.items() if k != "regime"},
                           symbol="BTCUSDC")
        ex = FakeExchange({"BTCUSDC": series}, quote_balance=10_000)
        ex.advance("BTCUSDC", steps=600)
        sys_ = TradingSystem(ex, ["BTCUSDC"], now_fn=lambda: clock["t"])
        for _ in range(3):
            ex.advance("BTCUSDC")
            clock["t"] += 60.0
            asyncio.run(sys_.tick())
        text = sys_.metrics.exposition()
        for series_name in (
                "portfolio_value_usd", "open_positions",
                "market_updates_total", "trading_signals_total",
                "signals_processed_total", "closed_trades",
                "tick_duration_seconds_bucket",
                'service_health{service="monitor"}',
                'ai_model_confidence{symbol="BTCUSDC"}'):
            assert f"crypto_trader_tpu_{series_name}" in text, series_name

    def test_launcher_logs_structured(self, tmp_path):
        from ai_crypto_trader_tpu.shell.exchange import FakeExchange
        from ai_crypto_trader_tpu.shell.launcher import TradingSystem
        from ai_crypto_trader_tpu.data.ingest import from_dict
        from ai_crypto_trader_tpu.data.synthetic import generate_ohlcv

        path = str(tmp_path / "trader.jsonl")
        d = generate_ohlcv(n=700, seed=3)
        series = from_dict({k: v for k, v in d.items() if k != "regime"},
                           symbol="BTCUSDC")
        ex = FakeExchange({"BTCUSDC": series}, quote_balance=10_000)
        sys_ = TradingSystem(ex, ["BTCUSDC"], log_path=path)
        assert sys_.log.path == path


class TestOutageGauges:
    def test_alert_gauges_emitted_on_outage_tick(self):
        """The gauges the alert rules watch (circuit_state, service_health,
        last_market_update_timestamp, max_positions) must be emitted on the
        ExchangeUnavailable tick path too — an open circuit is visible to
        Prometheus exactly DURING the outage, not after recovery."""
        from ai_crypto_trader_tpu.data.ingest import from_dict
        from ai_crypto_trader_tpu.data.synthetic import generate_ohlcv
        from ai_crypto_trader_tpu.shell.exchange import (
            ExchangeUnavailable, FakeExchange)
        from ai_crypto_trader_tpu.shell.launcher import TradingSystem

        series = from_dict(generate_ohlcv(n=700, seed=5), symbol="BTCUSDC")
        ex = FakeExchange({"BTCUSDC": series})
        system = TradingSystem(ex, ["BTCUSDC"], now_fn=lambda: 1000.0)

        async def down(*a, **kw):
            raise ExchangeUnavailable("venue down")

        system.monitor.poll = down
        out = asyncio.run(system.tick())
        assert "skipped" in out
        text = system.metrics.exposition()
        assert 'crypto_trader_tpu_circuit_state{breaker="exchange"}' in text
        assert "crypto_trader_tpu_last_market_update_timestamp" in text
        assert "crypto_trader_tpu_max_positions" in text


class TestStackConfigCoherence:
    def emitted_series(self):
        """Series names the code can emit, from the instrumentation sites."""
        import re

        names = set()
        for root, _, files in os.walk(os.path.join(REPO, "ai_crypto_trader_tpu")):
            for f in files:
                if not f.endswith(".py"):
                    continue
                src = open(os.path.join(root, f)).read()
                for m in re.finditer(
                        r'(?:set_gauge|inc|observe)\(\s*"([a-z0-9_]+)"', src):
                    names.add(m.group(1))
        return names

    def test_dashboard_queries_only_emitted_series(self):
        path = os.path.join(REPO, "monitoring/grafana/provisioning/"
                                  "dashboards/system_overview.json")
        dash = json.load(open(path))
        emitted = self.emitted_series()
        queried = set()
        for p in dash["panels"]:
            for t in p.get("targets", []):
                import re

                for m in re.finditer(r"crypto_trader_tpu_([a-z0-9_]+?)"
                                     r"(?:_bucket|_sum|_count)?[\{\[\)\s,]",
                                     t["expr"] + " "):
                    queried.add(m.group(1))
        unknown = queried - emitted
        assert not unknown, f"dashboard queries unemitted series: {unknown}"

    def test_prometheus_stack_configs_parse(self):
        """prometheus.yml and every rule file it references are valid YAML
        with the structure Prometheus expects (a broken rules file silently
        disables ALL alerting at deploy time)."""
        import yaml

        prom = yaml.safe_load(
            open(os.path.join(REPO, "monitoring/prometheus.yml")))
        assert prom["scrape_configs"], "no scrape configs"
        assert prom["rule_files"], "no rule files"
        for rf in prom["rule_files"]:
            rules = yaml.safe_load(
                open(os.path.join(REPO, "monitoring", rf)))
            assert rules["groups"], f"{rf}: no rule groups"
            for group in rules["groups"]:
                for rule in group["rules"]:
                    assert "expr" in rule, (rf, rule)
                    assert "alert" in rule or "record" in rule, (rf, rule)

    def test_rule_files_reference_only_emitted_series(self):
        """Every crypto_trader_tpu_* series named in an alert or recording
        rule must be one the code can emit — a renamed metric otherwise
        turns its alerts into silent no-data."""
        import re

        import yaml

        emitted = self.emitted_series()
        for fname in ("alert_rules.yml", "recording_rules.yml"):
            rules = yaml.safe_load(
                open(os.path.join(REPO, "monitoring", fname)))
            referenced = set()
            for group in rules["groups"]:
                for rule in group["rules"]:
                    for m in re.finditer(
                            r"crypto_trader_tpu_([a-z0-9_]+?)"
                            r"(?:_bucket|_sum|_count)?(?![a-z0-9_])",
                            rule["expr"]):
                        referenced.add(m.group(1))
            unknown = referenced - emitted
            assert not unknown, \
                f"{fname} references unemitted series: {unknown}"

    def test_compose_mounts_exist(self):
        import re

        compose = open(os.path.join(REPO, "docker-compose.yml")).read()
        for m in re.finditer(r"- (\./[^:]+):", compose):
            assert os.path.exists(os.path.join(REPO, m.group(1))), m.group(1)

    def test_grafana_provisioning_parses(self):
        base = os.path.join(REPO, "monitoring/grafana/provisioning")
        dash = json.load(open(os.path.join(
            base, "dashboards/system_overview.json")))
        assert dash["uid"] and len(dash["panels"]) >= 8
        for f in ("datasources/prometheus.yml", "dashboards/dashboard.yml"):
            content = open(os.path.join(base, f)).read()
            assert "apiVersion" in content

    def test_logstash_pipeline_matches_log_format(self, tmp_path):
        conf = open(os.path.join(REPO, "monitoring/logstash.conf")).read()
        assert "json" in conf and "*.jsonl" in conf
        # the logger writes what the pipeline expects: ts + json lines
        log = StructuredLogger("svc", path=str(tmp_path / "t.jsonl"))
        log.info("x")
        row = json.loads(open(str(tmp_path / "t.jsonl")).read())
        assert "ts" in row        # date filter matches [ "ts", "UNIX" ]
