"""Observability completion: structured logs, metric series, stack configs.

Covers utils/structlog.py (JSON-lines records, rotation, child loggers),
the launcher's Grafana-facing metric series, and coherence of the shipped
monitoring stack configs (Grafana provisioning panels query series the
code actually emits; compose mounts files that exist).
"""

import asyncio
import json
import os

import numpy as np
import pytest

from ai_crypto_trader_tpu.utils.structlog import StructuredLogger

REPO = os.path.join(os.path.dirname(__file__), "..")


class TestStructuredLogger:
    def test_json_lines_with_fields(self, tmp_path):
        path = str(tmp_path / "svc.jsonl")
        log = StructuredLogger("monitor", path=path, now_fn=lambda: 123.0)
        log.info("poll complete", symbols=2, latency_ms=4.5)
        log.error("boom", kind="exchange")
        rows = [json.loads(line) for line in open(path)]
        assert rows[0] == {"ts": 123.0, "level": "info", "service": "monitor",
                           "msg": "poll complete", "symbols": 2,
                           "latency_ms": 4.5}
        assert rows[1]["level"] == "error" and rows[1]["kind"] == "exchange"

    def test_min_level_filters(self, tmp_path):
        path = str(tmp_path / "svc.jsonl")
        log = StructuredLogger("x", path=path, min_level="warning")
        log.info("dropped")
        log.warning("kept")
        rows = [json.loads(line) for line in open(path)]
        assert [r["msg"] for r in rows] == ["kept"]

    def test_rotation(self, tmp_path):
        path = str(tmp_path / "svc.jsonl")
        log = StructuredLogger("x", path=path, max_bytes=500, backup_count=2)
        for i in range(100):
            log.info("filler message to push the file over the limit", i=i)
        assert os.path.exists(path + ".1")
        assert os.path.getsize(path) < 500 + 200   # fresh file after rotate

    def test_child_shares_sink_with_own_service(self, tmp_path):
        path = str(tmp_path / "svc.jsonl")
        log = StructuredLogger("launcher", path=path)
        log.child("executor").info("filled")
        rows = [json.loads(line) for line in open(path)]
        assert rows[0]["service"] == "executor"


class TestLauncherMetricSeries:
    @pytest.mark.slow
    def test_dashboard_series_emitted(self):
        from ai_crypto_trader_tpu.data.ingest import from_dict
        from ai_crypto_trader_tpu.data.synthetic import generate_ohlcv
        from ai_crypto_trader_tpu.shell.exchange import FakeExchange
        from ai_crypto_trader_tpu.shell.launcher import TradingSystem

        clock = {"t": 1_000_000.0}
        d = generate_ohlcv(n=1200, seed=3)
        series = from_dict({k: v for k, v in d.items() if k != "regime"},
                           symbol="BTCUSDC")
        ex = FakeExchange({"BTCUSDC": series}, quote_balance=10_000)
        ex.advance("BTCUSDC", steps=600)
        sys_ = TradingSystem(ex, ["BTCUSDC"], now_fn=lambda: clock["t"])
        for _ in range(3):
            ex.advance("BTCUSDC")
            clock["t"] += 60.0
            asyncio.run(sys_.tick())
        text = sys_.metrics.exposition()
        for series_name in (
                "portfolio_value_usd", "open_positions",
                "market_updates_total", "trading_signals_total",
                "signals_processed_total", "closed_trades",
                "tick_duration_seconds_bucket",
                'service_health{service="monitor"}',
                'ai_model_confidence{symbol="BTCUSDC"}'):
            assert f"crypto_trader_tpu_{series_name}" in text, series_name

    def test_launcher_logs_structured(self, tmp_path):
        from ai_crypto_trader_tpu.shell.exchange import FakeExchange
        from ai_crypto_trader_tpu.shell.launcher import TradingSystem
        from ai_crypto_trader_tpu.data.ingest import from_dict
        from ai_crypto_trader_tpu.data.synthetic import generate_ohlcv

        path = str(tmp_path / "trader.jsonl")
        d = generate_ohlcv(n=700, seed=3)
        series = from_dict({k: v for k, v in d.items() if k != "regime"},
                           symbol="BTCUSDC")
        ex = FakeExchange({"BTCUSDC": series}, quote_balance=10_000)
        sys_ = TradingSystem(ex, ["BTCUSDC"], log_path=path)
        assert sys_.log.path == path


class TestStackConfigCoherence:
    def emitted_series(self):
        """Series names the code can emit, from the instrumentation sites."""
        import re

        names = set()
        for root, _, files in os.walk(os.path.join(REPO, "ai_crypto_trader_tpu")):
            for f in files:
                if not f.endswith(".py"):
                    continue
                src = open(os.path.join(root, f)).read()
                for m in re.finditer(
                        r'(?:set_gauge|inc|observe)\(\s*"([a-z_]+)"', src):
                    names.add(m.group(1))
        return names

    def test_dashboard_queries_only_emitted_series(self):
        path = os.path.join(REPO, "monitoring/grafana/provisioning/"
                                  "dashboards/system_overview.json")
        dash = json.load(open(path))
        emitted = self.emitted_series()
        queried = set()
        for p in dash["panels"]:
            for t in p.get("targets", []):
                import re

                for m in re.finditer(r"crypto_trader_tpu_([a-z_]+?)"
                                     r"(?:_bucket|_sum|_count)?[\{\[\)\s,]",
                                     t["expr"] + " "):
                    queried.add(m.group(1))
        unknown = queried - emitted
        assert not unknown, f"dashboard queries unemitted series: {unknown}"

    def test_compose_mounts_exist(self):
        import re

        compose = open(os.path.join(REPO, "docker-compose.yml")).read()
        for m in re.finditer(r"- (\./[^:]+):", compose):
            assert os.path.exists(os.path.join(REPO, m.group(1))), m.group(1)

    def test_grafana_provisioning_parses(self):
        base = os.path.join(REPO, "monitoring/grafana/provisioning")
        dash = json.load(open(os.path.join(
            base, "dashboards/system_overview.json")))
        assert dash["uid"] and len(dash["panels"]) >= 8
        for f in ("datasources/prometheus.yml", "dashboards/dashboard.yml"):
            content = open(os.path.join(base, f)).read()
            assert "apiVersion" in content

    def test_logstash_pipeline_matches_log_format(self, tmp_path):
        conf = open(os.path.join(REPO, "monitoring/logstash.conf")).read()
        assert "json" in conf and "*.jsonl" in conf
        # the logger writes what the pipeline expects: ts + json lines
        log = StructuredLogger("svc", path=str(tmp_path / "t.jsonl"))
        log.info("x")
        row = json.loads(open(str(tmp_path / "t.jsonl")).read())
        assert "ts" in row        # date filter matches [ "ts", "UNIX" ]
