"""Pallas fused-EWMA kernel vs the associative-scan oracle (interpret mode
on CPU; the same kernel lowers natively on TPU)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from ai_crypto_trader_tpu.ops.pallas_kernels import T_TILE, fused_ewma


@pytest.fixture
def series(rng):
    return jnp.asarray(rng.normal(100, 5, (8, 2 * T_TILE)).astype(np.float32))


class TestFusedEWMA:
    def test_matches_scan_path(self, series):
        alphas = [2.0 / 13.0, 2.0 / 27.0, 1.0 / 14.0]
        ref = fused_ewma(series, alphas, force_pallas=False)
        out = fused_ewma(series, alphas, force_pallas=True, interpret=True)
        assert out.shape == (3, 8, 2 * T_TILE)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=1e-3)

    def test_seeded_with_first_value(self, series):
        out = fused_ewma(series, [0.1], force_pallas=True, interpret=True)
        np.testing.assert_allclose(np.asarray(out[0, :, 0]),
                                   np.asarray(series[:, 0]), rtol=1e-6)

    def test_carry_across_tiles(self, series):
        """Values right after a tile boundary must continue the recursion,
        not re-seed."""
        a = 0.25
        out = np.asarray(fused_ewma(series, [a], force_pallas=True,
                                    interpret=True))[0]
        x = np.asarray(series)
        t = T_TILE  # first position of tile 1
        expected = (1 - a) * out[:, t - 1] + a * x[:, t]
        np.testing.assert_allclose(out[:, t], expected, rtol=1e-5)

    def test_1d_input(self, series):
        out = fused_ewma(series[0], [0.2], force_pallas=True, interpret=True)
        assert out.shape == (1, 2 * T_TILE)

    def test_non_tile_length_falls_back(self, rng):
        x = jnp.asarray(rng.normal(0, 1, (4, 100)).astype(np.float32))
        out = fused_ewma(x, [0.3])        # auto-dispatch → scan path
        assert out.shape == (1, 4, 100)
