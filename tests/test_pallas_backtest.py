"""Pallas replay-scan kernel vs the lax.scan engine — exact stat parity.

The kernel (ops/pallas_backtest.py) must reproduce `engine.sweep`'s
BacktestStats bit-for-bit (same candles, same ops, same order) across
shapes that exercise the time/population padding paths and the per-candle
SL/TP override columns. Runs in interpreter mode on the CPU mesh; the
driver's TPU bench exercises the compiled path.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ai_crypto_trader_tpu import ops
from ai_crypto_trader_tpu.backtest import prepare_inputs, sample_params, sweep
from ai_crypto_trader_tpu.data import generate_ohlcv
from ai_crypto_trader_tpu.ops.pallas_backtest import BLOCK_B, CHUNK_T, sweep_pallas

# Slow tier (VERDICT r4 next#3): golden-parity / end-to-end /
# training / sharded-compile suite — deselected by the default
# run, executed via `pytest -m slow`.
pytestmark = pytest.mark.slow


def make_inputs(T, seed=3):
    d = generate_ohlcv(n=T, seed=seed)
    arrays = {k: jnp.asarray(v) for k, v in d.items() if k != "regime"}
    return prepare_inputs(ops.compute_indicators(arrays))


def assert_stats_equal(ref, got):
    for f in ref._fields:
        np.testing.assert_allclose(
            np.asarray(getattr(ref, f)), np.asarray(getattr(got, f)),
            rtol=1e-5, atol=1e-6, err_msg=f)


class TestParity:
    @pytest.mark.parametrize("T,B", [
        (CHUNK_T, BLOCK_B),            # exact tiles
        (1500, 130),                   # both axes padded
        (2 * CHUNK_T + 7, 64),         # time pad, small population
    ])
    def test_matches_engine(self, T, B):
        inp = make_inputs(T)
        params = sample_params(jax.random.PRNGKey(0), B)
        assert_stats_equal(sweep(inp, params),
                           sweep_pallas(inp, params, interpret=True))

    def test_with_sl_tp_overrides(self):
        inp = make_inputs(900)
        T = inp.close.shape[-1]
        key = jax.random.PRNGKey(1)
        # finite overrides on a random third of candles
        mask = jax.random.uniform(key, (T,)) < 0.33
        sl = jnp.where(mask, 1.5, jnp.nan)
        tp = jnp.where(mask, 3.0, jnp.nan)
        inp = inp._replace(sl_pct=sl, tp_pct=tp)
        params = sample_params(jax.random.PRNGKey(2), 32)
        assert_stats_equal(sweep(inp, params),
                           sweep_pallas(inp, params, interpret=True))

    def test_confidence_gating(self):
        inp = make_inputs(800)
        T = inp.close.shape[-1]
        conf = jnp.where(jnp.arange(T) % 3 == 0, 0.9, 0.2)
        inp = inp._replace(confidence=conf)
        params = sample_params(jax.random.PRNGKey(3), 16)
        ref = sweep(inp, params)
        got = sweep_pallas(inp, params, interpret=True)
        assert_stats_equal(ref, got)
        # the gate actually bit: the trade stream differs from ungated
        # (not necessarily fewer — blocking an entry changes the whole
        # downstream trajectory)
        ungated = sweep(inp._replace(confidence=jnp.ones((T,))), params)
        assert np.any(np.asarray(ref.total_trades)
                      != np.asarray(ungated.total_trades))

    def test_trades_happen(self):
        # guard against vacuous parity (two engines both doing nothing)
        inp = make_inputs(1500)
        params = sample_params(jax.random.PRNGKey(0), 64)
        got = sweep_pallas(inp, params, interpret=True)
        assert int(np.sum(np.asarray(got.total_trades))) > 0
