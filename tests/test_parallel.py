"""Mesh/sharding helpers: construction, shardings, padding."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from ai_crypto_trader_tpu.parallel import (
    data_sharding,
    make_mesh,
    pad_to_multiple,
    replicated,
    shard_leading_axis,
)

# Slow tier (VERDICT r4 next#3): golden-parity / end-to-end /
# training / sharded-compile suite — deselected by the default
# run, executed via `pytest -m slow`.
pytestmark = pytest.mark.slow


class TestMesh:
    def test_shapes(self, mesh8):
        assert mesh8.shape["data"] == 8 and mesh8.shape["model"] == 1

    def test_two_axis(self):
        mesh = make_mesh(data_parallel=4, model_parallel=2)
        assert mesh.shape["data"] == 4 and mesh.shape["model"] == 2

    def test_auto_data_parallel(self):
        mesh = make_mesh(model_parallel=2)
        assert mesh.shape["data"] == 4   # 8 devices / 2

    def test_oversubscription_raises(self):
        with pytest.raises(ValueError):
            make_mesh(data_parallel=16)
        with pytest.raises(ValueError):
            make_mesh(model_parallel=16)


class TestSharding:
    def test_data_sharding_places_shards(self, mesh8):
        x = jnp.arange(16.0).reshape(16, 1)
        y = jax.device_put(x, data_sharding(mesh8, ndim=2))
        assert len(y.sharding.device_set) == 8
        np.testing.assert_allclose(np.asarray(y), np.asarray(x))

    def test_replicated(self, mesh8):
        x = jnp.ones((4,))
        y = jax.device_put(x, replicated(mesh8))
        assert y.sharding.is_fully_replicated

    def test_shard_leading_axis_tree(self, mesh8):
        tree = {"a": jnp.ones((8, 3)), "b": jnp.zeros((16,))}
        out = shard_leading_axis(mesh8, tree)
        assert len(out["a"].sharding.device_set) == 8
        assert len(out["b"].sharding.device_set) == 8


class TestPadding:
    def test_pad_and_orig_size(self):
        x, orig = pad_to_multiple(np.ones((10, 3)), 8)
        assert x.shape == (16, 3) and orig == 10
        np.testing.assert_allclose(x[10:], 0.0)

    def test_already_aligned_untouched(self):
        x = np.ones((16, 2))
        y, orig = pad_to_multiple(x, 8)
        assert y.shape == (16, 2) and orig == 16

    def test_pad_other_axis(self):
        x, orig = pad_to_multiple(np.ones((3, 10)), 4, axis=1, pad_value=-1.0)
        assert x.shape == (3, 12) and orig == 10
        np.testing.assert_allclose(x[:, 10:], -1.0)
