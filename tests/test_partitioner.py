"""The Partitioner seam + the scanned-GA contract (tier-1).

Pins the ISSUE 11 guarantees:
  * partition rules: regex → PartitionSpec, scalars never partitioned,
    uncovered leaves raise;
  * population_eval: single-device fallback ≡ sharded results on a
    1-device mesh AND an 8-device mesh, pad + mask for populations that
    don't divide the device count;
  * the scanned GA: bit-exact against the legacy Python-loop driver on
    the same PRNGKey, ONE host_read per run, ZERO recompiles on a repeat
    run (the regression guard), and a verified genome-buffer donation.

Cheap deterministic fitness keeps this tier-1; the same contracts on the
REAL backtest fitness live in the slow tier (tests/test_evolve.py).
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import PartitionSpec as P

from ai_crypto_trader_tpu.backtest.strategy import (
    _HIGHS,
    _LOWS,
    stack_params,
)
from ai_crypto_trader_tpu.config import GAParams
from ai_crypto_trader_tpu.evolve import run_ga
from ai_crypto_trader_tpu.evolve import ga as ga_mod
from ai_crypto_trader_tpu.evolve.ga import run_ga_legacy
from ai_crypto_trader_tpu.parallel import (
    MeshPartitioner,
    SingleDevicePartitioner,
    get_partitioner,
    make_mesh,
    match_partition_rules,
)
from ai_crypto_trader_tpu.utils import devprof


def _cheap_fitness(p):
    """Deterministic nontrivial fitness with NO backtest: distance of the
    genome from a fixed target point, so the GA has a real gradient to
    climb while the whole program compiles in well under a second."""
    g = jnp.stack(list(p))
    target = jnp.asarray((_LOWS + 0.75 * (_HIGHS - _LOWS)), jnp.float32)
    span = jnp.asarray(_HIGHS - _LOWS, jnp.float32)
    return -jnp.sum(((g - target) / span) ** 2)


CFG = GAParams(population_size=8, generations=3, elite_size=2)


class TestPartitionRules:
    def test_regex_rules_and_scalar_passthrough(self):
        tree = {"dense": {"kernel": np.ones((4, 8)), "bias": np.ones((8,))},
                "scale": np.float32(2.0)}
        specs = match_partition_rules(
            [(r"kernel", P(None, "model")), (r".*", P())], tree)
        assert specs["dense"]["kernel"] == P(None, "model")
        assert specs["dense"]["bias"] == P()
        assert specs["scale"] == P()          # scalars never partitioned

    def test_uncovered_leaf_raises(self):
        with pytest.raises(ValueError, match="no partition rule"):
            match_partition_rules([(r"kernel", P())],
                                  {"other": np.ones((3, 3))})


class TestPopulationEval:
    """population_eval over a toy per-member function: mesh invariance and
    pad + mask."""

    @staticmethod
    def _fn(tree):
        return {"sq": tree["x"] ** 2, "sum": jnp.sum(tree["x"], axis=-1)}

    @staticmethod
    def _fn_repl(tree, extra):
        return tree["x"] * extra["scale"]

    def test_single_device_fallback_matches_one_device_mesh(self):
        x = {"x": jnp.arange(24.0).reshape(6, 4)}
        single = SingleDevicePartitioner().population_eval(self._fn)(x)
        mesh1 = make_mesh(data_parallel=1, model_parallel=1,
                          devices=jax.devices()[:1])
        onedev = MeshPartitioner(mesh1).population_eval(self._fn)(x)
        for k in single:
            np.testing.assert_array_equal(np.asarray(single[k]),
                                          np.asarray(onedev[k]))

    def test_pad_and_mask_uneven_population(self, mesh8):
        # 10 members over 8 devices: pad to 16 inside, slice back to 10
        x = {"x": jnp.arange(40.0).reshape(10, 4)}
        plain = SingleDevicePartitioner().population_eval(self._fn)(x)
        sharded = MeshPartitioner(mesh8).population_eval(self._fn)(x)
        assert sharded["sq"].shape == (10, 4)
        assert sharded["sum"].shape == (10,)
        for k in plain:
            np.testing.assert_array_equal(np.asarray(plain[k]),
                                          np.asarray(sharded[k]))

    def test_replicated_args_ride_whole(self, mesh8):
        x = {"x": jnp.arange(16.0).reshape(8, 2)}
        extra = {"scale": jnp.asarray(3.0)}
        got = MeshPartitioner(mesh8).population_eval(self._fn_repl)(x, extra)
        np.testing.assert_array_equal(np.asarray(got),
                                      np.asarray(x["x"]) * 3.0)

    def test_get_partitioner_explicit_meshes(self, mesh8):
        mesh1 = make_mesh(data_parallel=1, model_parallel=1,
                          devices=jax.devices()[:1])
        assert isinstance(get_partitioner(mesh1), SingleDevicePartitioner)
        part = get_partitioner(mesh8)
        assert isinstance(part, MeshPartitioner)
        assert part.device_count == 8
        assert len(part.trial_devices()) == 8

    def test_shard_population_places_leading_axis(self, mesh8):
        part = MeshPartitioner(mesh8)
        tree = {"g": jnp.ones((16, 3))}
        out = part.shard_population(tree)
        assert len(out["g"].sharding.device_set) == 8


class TestScannedGA:
    def test_bit_exact_vs_legacy_loop(self):
        b_scan, h_scan = run_ga(jax.random.PRNGKey(7), _cheap_fitness, CFG)
        b_leg, h_leg = run_ga_legacy(jax.random.PRNGKey(7), _cheap_fitness,
                                     CFG)
        assert len(h_scan) == CFG.generations
        for a, b in zip(b_scan, b_leg):
            assert float(a) == float(b)
        for ha, hb in zip(h_scan, h_leg):
            assert ha["generation"] == hb["generation"]
            assert ha["best_fitness"] == hb["best_fitness"]
            # mean/diversity may differ by an f32 ULP: the scan fuses the
            # reductions into one program, the legacy loop runs them as
            # standalone eager reductions
            np.testing.assert_allclose(ha["mean_fitness"],
                                       hb["mean_fitness"],
                                       rtol=2e-6, atol=1e-7)
            np.testing.assert_allclose(ha["diversity"], hb["diversity"],
                                       rtol=2e-6, atol=1e-7)

    def test_seed_params_ride_individual_zero(self):
        from ai_crypto_trader_tpu.backtest import default_params

        b1, _ = run_ga(jax.random.PRNGKey(3), _cheap_fitness, CFG,
                       seed_params=default_params())
        b2, _ = run_ga_legacy(jax.random.PRNGKey(3), _cheap_fitness, CFG,
                              seed_params=default_params())
        for a, b in zip(b1, b2):
            assert float(a) == float(b)

    def test_one_dispatch_one_sync_zero_recompile(self, monkeypatch):
        """THE regression guard: a repeat run with the same (fitness, cfg,
        partitioner) must re-trace nothing and sync the host exactly once,
        and the donated genome buffer must actually be consumed.  The
        zero-recompile assertion rides the meshprof RecompileSentinel —
        the same watch-window counter the SteadyStateRecompile alert
        pages on in production (utils/meshprof.py)."""
        from ai_crypto_trader_tpu.utils import meshprof

        def fitness(p):                     # fresh closure → fresh program
            return _cheap_fitness(p)

        dp = devprof.DevProf()
        mp = meshprof.MeshProf()
        syncs = {"n": 0}
        real_read = ga_mod.host_read

        def counting_read(tree):
            syncs["n"] += 1
            return real_read(tree)

        monkeypatch.setattr(ga_mod, "host_read", counting_read)
        with devprof.use(dp), meshprof.use(mp):
            run_ga(jax.random.PRNGKey(0), fitness, CFG)   # compile run
            assert syncs["n"] == 1
            card = dp.cards["ga_scan"]
            assert card.error is None
            assert card.flops > 0
            assert card.donation_ok is True               # no silent copy
            # the compile run is COLD (fresh program-cache entry): its
            # compiles attribute to warmup, never to steady state
            assert mp.recompiles.steady_total() == 0

            _, hist = run_ga(jax.random.PRNGKey(1), fitness, CFG)
            assert mp.recompiles.steady_total() == 0, \
                mp.recompiles.status()                    # zero recompiles
            assert mp.recompiles.windows["ga_scan"] == 2
            assert mp.transfers.total() == 0              # no guarded pulls
            assert syncs["n"] == 2                        # ONE more sync
            # the single-device layout card rode the compile run
            assert mp.layouts["ga_scan"].devices == 1
        assert len(hist) == CFG.generations
        assert all(np.isfinite(h["best_fitness"]) for h in hist)

    def test_mesh_partitioned_ga_matches_single(self, mesh8):
        fit = _cheap_fitness
        b_single, h_single = run_ga(jax.random.PRNGKey(11), fit, CFG)
        b_mesh, h_mesh = run_ga(jax.random.PRNGKey(11), fit, CFG,
                                partitioner=MeshPartitioner(mesh8))
        for a, b in zip(b_single, b_mesh):
            assert float(a) == float(b)
        for ha, hb in zip(h_single, h_mesh):
            assert ha["best_fitness"] == hb["best_fitness"]

    def test_elitism_monotone_best(self):
        _, hist = run_ga(jax.random.PRNGKey(5), _cheap_fitness,
                         GAParams(population_size=8, generations=5,
                                  elite_size=2))
        bf = [h["best_fitness"] for h in hist]
        assert all(b2 >= b1 - 1e-6 for b1, b2 in zip(bf, bf[1:]))

    def test_uneven_population_on_mesh(self, mesh8):
        """pop 10 over 8 devices: the eval pads + masks inside the scan."""
        cfg = GAParams(population_size=10, generations=2, elite_size=2)
        b_mesh, h_mesh = run_ga(jax.random.PRNGKey(13), _cheap_fitness, cfg,
                                partitioner=MeshPartitioner(mesh8))
        b_single, _ = run_ga(jax.random.PRNGKey(13), _cheap_fitness, cfg)
        assert len(h_mesh) == 2
        for a, b in zip(b_mesh, b_single):
            assert float(a) == float(b)


class TestGenomeRoundTrip:
    def test_stack_matches_genome_width(self):
        from ai_crypto_trader_tpu.backtest import default_params

        g = stack_params(default_params())
        assert g.shape == (_LOWS.shape[0],)
