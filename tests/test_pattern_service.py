"""ChartPatternService: signal derivation, gating cadence, combined report.

Pins `services/pattern_recognition_service.py` semantics: the
completion→strength ladder and 0.3 floor (`pattern_recognition.py:
1147-1214`, :748-756), interval-gated analysis (:150-156), publication
rules (:209-221), and the 5-minute combined report (:298-343).
"""

import asyncio

import numpy as np
import pytest

import ai_crypto_trader_tpu.patterns.service as svc_mod
from ai_crypto_trader_tpu.patterns import (
    ChartPatternService,
    PatternRecognizer,
    pattern_trading_signals,
)
from ai_crypto_trader_tpu.shell.bus import EventBus


def analysis(pattern="double_bottom", confidence=0.8, completion=0.8,
             bias="bullish"):
    return {"detected": True, "primary_pattern": pattern,
            "confidence": confidence, "completion": completion,
            "implications": {"bias": bias, "confirmation": "c",
                             "invalidation": "i"}}


class TestSignalDerivation:
    def test_strength_ladder(self):
        # completion 95% → very_strong 0.9 × conf × completion
        s = pattern_trading_signals(analysis(confidence=1.0, completion=0.95))
        assert s["signal_strength"] == "very_strong"
        assert s["strength"] == pytest.approx(round(0.9 * 1.0 * 0.95, 2))
        s = pattern_trading_signals(analysis(confidence=1.0, completion=0.80))
        assert s["signal_strength"] == "strong"
        s = pattern_trading_signals(analysis(confidence=1.0, completion=0.60))
        assert s["signal_strength"] == "moderate"
        s = pattern_trading_signals(analysis(confidence=1.0, completion=0.40))
        assert s["signal_strength"] == "weak"

    def test_bias_to_signal_with_floor(self):
        assert pattern_trading_signals(
            analysis(bias="bullish", confidence=0.9, completion=0.9)
        )["signal"] == "buy"
        assert pattern_trading_signals(
            analysis(bias="bearish", confidence=0.9, completion=0.9)
        )["signal"] == "sell"
        # strong bias but strength ≤ 0.3 → neutral (the 0.3 floor)
        weak = pattern_trading_signals(
            analysis(bias="bullish", confidence=0.55, completion=0.55))
        assert weak["strength"] <= 0.3 and weak["signal"] == "neutral"

    def test_confidence_threshold_gates(self):
        s = pattern_trading_signals(analysis(confidence=0.4))
        assert s == {"signal": "neutral", "strength": 0.0}

    def test_not_detected_neutral(self):
        assert pattern_trading_signals({"detected": False})["signal"] == "neutral"


def make_klines(n=120, seed=0):
    rng = np.random.default_rng(seed)
    close = 100 * np.cumprod(1 + rng.normal(0, 0.004, n))
    return [[i, close[i] * 0.999, close[i] * 1.002, close[i] * 0.997,
             close[i], 1000.0] for i in range(n)]


class Clock:
    def __init__(self):
        self.t = 1_000_000.0

    def __call__(self):
        return self.t


@pytest.fixture()
def service(monkeypatch):
    bus = EventBus()
    bus.set("historical_data_BTCUSDC_5m", make_klines())
    clock = Clock()
    svc = ChartPatternService(bus, PatternRecognizer("cnn", params=None),
                              ["BTCUSDC"], now_fn=clock)
    svc.clock = clock
    # deterministic detection: the compiled scorer is exercised in
    # test_patterns.py; here the cadence/publication logic is under test
    monkeypatch.setattr(svc_mod, "detect_patterns",
                        lambda *a, **k: analysis(confidence=0.9,
                                                 completion=0.9))
    return svc


class TestServiceCadence:
    def test_publishes_strong_signal(self, service):
        out = asyncio.run(service.run_once())
        assert out["published"] == 1
        sig = service.bus.get("pattern_signals_BTCUSDC")
        assert sig["signal"] == "buy" and sig["source"] == "pattern_recognition"
        assert service.bus.published_counts.get("pattern_signals") == 1
        assert service.bus.get("pattern_analysis_BTCUSDC")["detected"]

    def test_interval_gate(self, service):
        asyncio.run(service.run_once())
        service.clock.t += 299
        out = asyncio.run(service.run_once())
        assert out["published"] == 0          # gated
        service.clock.t += 2
        out = asyncio.run(service.run_once())
        assert out["published"] == 1          # past update_interval

    def test_weak_signal_not_published(self, service, monkeypatch):
        monkeypatch.setattr(svc_mod, "detect_patterns",
                            lambda *a, **k: analysis(confidence=0.55,
                                                     completion=0.55))
        out = asyncio.run(service.run_once())
        assert out["published"] == 0
        assert service.bus.get("pattern_signals_BTCUSDC") is None
        # analysis is still stored for the combined report
        assert service.bus.get("pattern_analysis_BTCUSDC") is not None

    def test_no_data_skips(self, service):
        service.symbols = ["NODATAUSDC"]
        out = asyncio.run(service.run_once())
        assert out["published"] == 0

    def test_prefers_5m_over_1m(self, service):
        service.bus.set("historical_data_BTCUSDC_1m", make_klines(seed=9))
        arr = service._ohlcv("BTCUSDC")
        want = np.asarray([r[1:6] for r in
                           service.bus.get("historical_data_BTCUSDC_5m")],
                          np.float32)
        np.testing.assert_array_equal(arr, want)

    def test_falls_back_to_1m(self, service):
        service.bus.set("historical_data_BTCUSDC_5m", None)
        service.bus.set("historical_data_BTCUSDC_1m", make_klines(seed=9))
        arr = service._ohlcv("BTCUSDC")
        want = np.asarray([r[1:6] for r in
                           service.bus.get("historical_data_BTCUSDC_1m")],
                          np.float32)
        np.testing.assert_array_equal(arr, want)


class TestCombinedReport:
    def test_report_counts_and_strongest(self, service):
        service.pattern_data = {
            "A": analysis(bias="bullish", confidence=0.9, completion=0.95),
            "B": analysis(bias="bearish", confidence=0.8, completion=0.8),
            "C": analysis(confidence=0.3),      # below threshold → excluded
        }
        rep = service.combined_report(service.clock.t)
        assert rep["summary"]["bullish_patterns"] == 1
        assert rep["summary"]["bearish_patterns"] == 1
        assert rep["summary"]["neutral_patterns"] == 1   # C: analyzed, no signal
        assert rep["summary"]["strongest_signal"]["symbol"] == "A"
        assert set(rep["signals"]) == {"A", "B"}

    def test_report_cadence(self, service):
        out = asyncio.run(service.run_once())
        assert out["reported"]
        assert service.bus.get("pattern_analysis_report") is not None
        service.clock.t += 299
        assert not asyncio.run(service.run_once())["reported"]
        service.clock.t += 2
        assert asyncio.run(service.run_once())["reported"]
