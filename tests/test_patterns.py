"""Pattern recognition: generators produce distinguishable shapes, the
classifier learns them, detection gates on confidence."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from ai_crypto_trader_tpu.patterns import (
    PATTERN_CLASSES,
    PATTERN_IMPLICATIONS,
    detect_patterns,
    generate_dataset,
    generate_pattern,
    preprocess_window,
    train_pattern_model,
)

KEY = jax.random.PRNGKey(7)


class TestSynthetic:
    @pytest.mark.slow
    def test_all_classes_generate(self):
        for label in range(len(PATTERN_CLASSES)):
            path = generate_pattern(jax.random.fold_in(KEY, label), label, T=60)
            assert path.shape == (60,)
            assert np.isfinite(np.asarray(path)).all(), PATTERN_CLASSES[label]

    @pytest.mark.slow
    def test_dataset_shapes_and_labels(self):
        X, y = generate_dataset(KEY, n_per_class=4, T=60)
        assert X.shape == (4 * 15, 60, 5)
        assert set(np.unique(np.asarray(y))) == set(range(15))
        assert np.isfinite(np.asarray(X)).all()

    def test_double_top_has_two_peaks(self):
        from scipy.signal import find_peaks
        label = PATTERN_CLASSES.index("double_top")
        paths = jax.vmap(lambda k: generate_pattern(k, label, T=100))(
            jax.random.split(KEY, 8))
        two_peak_count = 0
        for p in np.asarray(paths):
            sm = np.convolve(p, np.ones(7) / 7, "same")
            peaks, _ = find_peaks(sm[5:-5], prominence=2.0)
            if len(peaks) >= 2:
                two_peak_count += 1
        assert two_peak_count >= 6


class TestPreprocess:
    def test_normalization(self):
        w = np.abs(np.random.default_rng(0).normal(100, 5, (60, 5))).astype(np.float32)
        out = np.asarray(preprocess_window(jnp.asarray(w)))
        np.testing.assert_allclose(out[-1, 3], 1.0, rtol=1e-5)  # close ÷ last close
        assert out[:, 4].max() <= 1.0 + 1e-6


@pytest.mark.slow
class TestModelTraining:
    @pytest.fixture(scope="class")
    def recognizer(self):
        return train_pattern_model(KEY, "cnn", n_per_class=24, epochs=6)

    def test_loss_decreases(self, recognizer):
        losses = [h["loss"] for h in recognizer.history]
        assert losses[-1] < losses[0] * 0.7

    def test_classifies_held_out_patterns(self, recognizer):
        X, y = generate_dataset(jax.random.PRNGKey(99), n_per_class=8)
        logits = recognizer.logits(jnp.asarray(X))
        acc = (np.asarray(jnp.argmax(logits, -1)) == np.asarray(y)).mean()
        assert acc > 0.5, f"held-out accuracy {acc:.2f}"

    def test_detect_on_planted_pattern(self, recognizer):
        label = PATTERN_CLASSES.index("double_top")
        from ai_crypto_trader_tpu.patterns.synthetic import to_ohlcv
        k1, k2 = jax.random.split(KEY)
        close = generate_pattern(k1, label, T=60)
        # rebuild raw ohlcv from the normalized window (scale back up)
        win = np.asarray(to_ohlcv(k2, close)) * 100.0
        out = detect_patterns(recognizer, win, seq_len=60, stride=5,
                              confidence_threshold=0.2)
        assert "top_patterns" in out
        assert len(out["top_patterns"]) == 3
        if out["detected"]:
            assert out["implications"]["bias"] in ("bullish", "bearish",
                                                   "neutral", "continuation")
            assert 0 < out["completion"] <= 1.0

    def test_insufficient_data(self, recognizer):
        out = detect_patterns(recognizer, np.ones((10, 5), np.float32))
        assert out["detected"] is False

    @pytest.mark.parametrize("mt", ["lstm", "cnn_lstm"])
    def test_other_architectures_train(self, mt):
        rec = train_pattern_model(KEY, mt, n_per_class=8, epochs=2)
        assert np.isfinite(rec.history[-1]["loss"])


class TestImplications:
    def test_every_class_has_rules(self):
        for name in PATTERN_CLASSES:
            imp = PATTERN_IMPLICATIONS[name]
            assert {"bias", "action", "confirmation", "invalidation"} <= set(imp)
