"""Pipelined tick path (double-buffered ring + async host_read) and the
persistent AOT compile cache.

The pipelined engine's contract is PARITY SHIFTED BY ONE TICK: the same
tape through serial and pipelined engines yields byte-identical outputs,
delivered one step later; the monitor carries the matching publish
context so the published `market_updates` payloads are byte-identical
too.  The serial path (default) stays the oracle — the pipelined toggle
is ONE ctor knob, which is exactly what these tests flip.

The failure contract: a wedged drain drops everything in flight and
re-seeds the ring (transfer, never a compile, never a duplicate publish);
a stale/contended/corrupt compile cache degrades to a recompile, never a
crash (docs/RESILIENCE.md rows)."""

import asyncio
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from ai_crypto_trader_tpu.data.ingest import OHLCV
from ai_crypto_trader_tpu.data.synthetic import generate_ohlcv
from ai_crypto_trader_tpu.ops import tick_engine
from ai_crypto_trader_tpu.ops.tick_engine import TickEngine
from ai_crypto_trader_tpu.shell.bus import EventBus
from ai_crypto_trader_tpu.shell.exchange import FakeExchange
from ai_crypto_trader_tpu.shell.monitor import MarketMonitor
from ai_crypto_trader_tpu.utils import aotcache

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LIMIT = 128          # same compiled shape bucket as tests/test_stream.py


def _series(n=900, seed=7, symbol="BTCUSDC"):
    d = generate_ohlcv(n=n, seed=seed)
    return OHLCV(timestamp=np.arange(n, dtype=np.int64) * 60_000,
                 open=d["open"], high=d["high"], low=d["low"],
                 close=d["close"], volume=d["volume"] * 1000, symbol=symbol)


def _exchange(symbols=("BTCUSDC", "ETHUSDC"), n=900, advance=700):
    ex = FakeExchange({s: _series(n=n, seed=7 + i, symbol=s)
                       for i, s in enumerate(symbols)})
    ex.advance(steps=advance)
    return ex


def _feed(eng, ex, symbols, intervals):
    for s in symbols:
        for iv in intervals:
            eng.ingest(s, iv, ex.get_klines(s, iv, LIMIT))


def _assert_tree_equal(a, b, where=""):
    """Byte-identical pytree-of-arrays comparison (dicts of arrays and
    nested dicts — the engine's host output)."""
    assert set(a) == set(b), (where, set(a) ^ set(b))
    for k in b:
        if isinstance(b[k], dict):
            _assert_tree_equal(a[k], b[k], f"{where}/{k}")
        else:
            np.testing.assert_array_equal(
                np.asarray(a[k]), np.asarray(b[k]), err_msg=f"{where}/{k}")


class TestEnginePipelined:
    def test_outputs_match_serial_shifted_one_tick(self):
        """The tentpole parity: pipelined tick T returns serial tick T−1's
        output byte for byte (same ring, same scatter, same program);
        flush() delivers the final tick."""
        symbols, ivs = ("BTCUSDC", "ETHUSDC"), ("1m", "3m")
        ex_s = _exchange(symbols)
        ex_p = _exchange(symbols)      # identical tape, independent cursor
        serial = TickEngine(list(symbols), list(ivs), window=LIMIT)
        pipe = TickEngine(list(symbols), list(ivs), window=LIMIT,
                          pipelined=True)
        serial_outs = []
        pipe_outs = []
        for i in range(5):
            _feed(serial, ex_s, symbols, ivs)
            _feed(pipe, ex_p, symbols, ivs)
            serial_outs.append(serial.step())
            got = pipe.step()
            if i == 0:
                assert got is None                 # pipeline fill
                assert pipe.last_stats["inflight"]
            else:
                pipe_outs.append(got)
            ex_s.advance(steps=1)
            ex_p.advance(steps=1)
        pipe_outs.append(pipe.flush())             # the final inflight tick
        assert pipe.flush() is None                # idempotent drain
        assert len(pipe_outs) == len(serial_outs) == 5
        for i, (a, b) in enumerate(zip(pipe_outs, serial_outs)):
            _assert_tree_equal(a, b, f"tick{i}")
        assert serial.dispatch_count == pipe.dispatch_count == 5

    def test_contract_one_host_read_zero_steady_recompiles(self, monkeypatch):
        """The serial poll contract, pipelined: ONE host_read per steady
        step (the drain), zero steady-window recompiles even though the
        dispatch alternates buffers, and donation verified on BOTH
        buffers."""
        from ai_crypto_trader_tpu.utils import devprof, meshprof

        symbols, ivs = ("BTCUSDC", "ETHUSDC"), ("1m",)
        ex = _exchange(symbols)
        eng = TickEngine(list(symbols), list(ivs), window=LIMIT,
                         pipelined=True)
        syncs = {"n": 0}
        real_read = tick_engine.host_read

        def counting_read(tree):
            syncs["n"] += 1
            return real_read(tree)

        monkeypatch.setattr(tick_engine, "host_read", counting_read)
        mp = meshprof.MeshProf()
        with devprof.use(devprof.DevProf()), meshprof.use(mp):
            _feed(eng, ex, symbols, ivs)
            assert eng.step() is None              # seed + compile, fill
            assert syncs["n"] == 0                 # nothing drained yet
            for tick in range(1, 4):               # steady state
                ex.advance(steps=1)
                _feed(eng, ex, symbols, ivs)
                assert eng.step() is not None
                assert syncs["n"] == tick          # ONE read per step
                # drained stats describe the PREVIOUS dispatch (the tick
                # just collected): tick 1 drains the seed itself
                stats = eng.last_stats
                assert stats["full_seed"] == (tick == 1)
                assert stats["overlap_reclaimed_s"] >= 0.0
        # the sentinel saw ZERO steady compiles across BOTH buffers (the
        # two rings share one compiled shape) and donation was verified
        # on each buffer's first profiled dispatch
        assert mp.recompiles.steady_total() == 0, mp.recompiles.status()
        assert eng._donation_checked == [True, True]
        assert eng.dispatch_count == 4
        # doubled scatter capacity: a buffer consumes up to TWO polls
        assert eng.last_stats["scatter_capacity"] == \
            eng._ring_np.shape[0] * eng._ring_np.shape[1] * eng.max_new * 2

    def test_failed_drain_reseeds_not_wedges(self, monkeypatch):
        """RESILIENCE row: a drain that dies (device reset, XLA abort)
        drops every in-flight buffer, re-seeds on the next step, and the
        post-recovery outputs still match the serial oracle."""
        symbols, ivs = ("BTCUSDC",), ("1m",)
        ex_p = _exchange(symbols)
        ex_s = _exchange(symbols)
        eng = TickEngine(list(symbols), list(ivs), window=LIMIT,
                         pipelined=True)
        oracle = TickEngine(list(symbols), list(ivs), window=LIMIT)
        _feed(eng, ex_p, symbols, ivs)
        _feed(oracle, ex_s, symbols, ivs)
        assert eng.step() is None
        oracle.step()

        real_read = tick_engine.host_read

        def dying_read(tree):
            raise RuntimeError("device wedged mid-readback")

        monkeypatch.setattr(tick_engine, "host_read", dying_read)
        ex_p.advance(steps=1)
        ex_s.advance(steps=1)
        _feed(eng, ex_p, symbols, ivs)
        _feed(oracle, ex_s, symbols, ivs)
        with pytest.raises(RuntimeError, match="wedged"):
            eng.step()                             # drain of tick 1 dies
        # pipeline fully aborted: nothing in flight, both buffers dropped,
        # next step re-seeds from the host mirror
        assert eng._inflight is None
        assert eng._bufs == [None, None]
        assert eng._need_seed
        monkeypatch.setattr(tick_engine, "host_read", real_read)
        oracle.step()                              # oracle saw tick 2 too
        assert eng.step() is None                  # re-seed + re-fill
        assert eng.last_stats["full_seed"]
        ex_p.advance(steps=1)
        ex_s.advance(steps=1)
        _feed(eng, ex_p, symbols, ivs)
        _feed(oracle, ex_s, symbols, ivs)
        serial_out = oracle.step()                 # tick 3 oracle
        got = None
        # tick 3's step drains tick 2 (dropped tick's successor): advance
        # once more so the drained output lines up with the oracle's t=3
        got = eng.step()                           # drains the re-seeded t2
        assert got is not None
        final = eng.flush()                        # t3
        _assert_tree_equal(final, serial_out, "post-recovery")


class TestMonitorPipelinedParity:
    def _run(self, pipelined: bool, ticks: int = 6):
        symbols = ("BTCUSDC", "ETHUSDC")
        ex = _exchange(symbols)
        clock = {"t": 0.0}
        bus = EventBus()
        q = bus.subscribe("market_updates")
        mon = MarketMonitor(bus, ex, symbols=list(symbols),
                            now_fn=lambda: clock["t"], kline_limit=LIMIT,
                            fused=True, pipelined=pipelined)

        async def go():
            await mon.poll(force=True)
            for _ in range(ticks):
                ex.advance(steps=1)
                clock["t"] += 60.0
                await mon.poll()
            await mon.flush_pipeline()

        asyncio.run(go())
        out = []
        while not q.empty():
            env = q.get_nowait()
            out.append(env["data"])        # the envelope stamps publish-
            #                                time ts; the PAYLOAD is data
        return out

    def test_published_payloads_byte_identical(self):
        """Satellite (c): the pipelined monitor publishes the SAME
        market_updates as the serial monitor at matched ticks — every
        field byte-identical, including the carried event-time ages."""
        serial = self._run(pipelined=False)
        pipe = self._run(pipelined=True)
        assert len(serial) == len(pipe) > 0
        for i, (a, b) in enumerate(zip(pipe, serial)):
            assert a == b, (i, {k: (a.get(k), b.get(k))
                                for k in set(a) | set(b)
                                if a.get(k) != b.get(k)})

    def test_drain_crash_no_duplicate_publish(self, monkeypatch):
        """Kill the readback between dispatch and drain: the poll raises
        (stage-skip semantics), the pending publish context dies with the
        pipeline, and recovery re-seeds — every published (symbol,
        candle-timestamp) pair is unique across the whole run."""
        symbols = ("BTCUSDC",)
        ex = _exchange(symbols)
        clock = {"t": 0.0}
        bus = EventBus()
        q = bus.subscribe("market_updates")
        mon = MarketMonitor(bus, ex, symbols=list(symbols),
                            now_fn=lambda: clock["t"], kline_limit=LIMIT,
                            fused=True, pipelined=True)
        real_read = tick_engine.host_read

        def dying_read(tree):
            raise RuntimeError("wedged drain")

        async def go():
            await mon.poll(force=True)             # dispatch t0, fill
            ex.advance(steps=1)
            clock["t"] += 60.0
            monkeypatch.setattr(tick_engine, "host_read", dying_read)
            with pytest.raises(RuntimeError, match="wedged"):
                await mon.poll()                   # drain of t0 dies
            assert mon._pending_pub is None        # context died with it
            assert mon._engine._need_seed
            monkeypatch.setattr(tick_engine, "host_read", real_read)
            for _ in range(3):
                ex.advance(steps=1)
                clock["t"] += 60.0
                await mon.poll()                   # re-seed + steady
            await mon.flush_pipeline()

        asyncio.run(go())
        seen = set()
        while not q.empty():
            upd = q.get_nowait()["data"]
            key = (upd["symbol"], upd["timestamp"])
            assert key not in seen, f"duplicate publish {key}"
            seen.add(key)
        assert len(seen) >= 2                      # recovery kept publishing


class TestPrecision:
    def test_bf16_decide_parity_within_tolerance(self):
        """Satellite (c): the bf16 knob keeps decisions within tolerance
        of f32 (exactly equal where the backend has no reduced-precision
        path — the knob is a matmul-precision hint, not a dtype cast)."""
        symbols, ivs = ("BTCUSDC",), ("1m",)
        ex_a = _exchange(symbols)
        ex_b = _exchange(symbols)
        f32 = TickEngine(list(symbols), list(ivs), window=LIMIT)
        bf16 = TickEngine(list(symbols), list(ivs), window=LIMIT,
                          precision="bf16")
        assert bf16.precision == "bf16"
        _feed(f32, ex_a, symbols, ivs)
        _feed(bf16, ex_b, symbols, ivs)
        a, b = f32.step(), bf16.step()

        def walk(x, y, where):
            for k in y:
                if isinstance(y[k], dict):
                    walk(x[k], y[k], f"{where}/{k}")
                else:
                    np.testing.assert_allclose(
                        np.asarray(x[k], np.float64),
                        np.asarray(y[k], np.float64),
                        rtol=5e-2, atol=5e-2, err_msg=f"{where}/{k}")

        walk(b, a, "bf16")

    def test_invalid_precision_rejected_eagerly(self):
        with pytest.raises(ValueError):
            TickEngine(["BTCUSDC"], ["1m"], window=LIMIT, precision="fp8")

    def test_tenant_engine_validates_precision(self):
        from ai_crypto_trader_tpu.ops.tenant_engine import TenantEngine

        with pytest.raises(ValueError):
            TenantEngine(["BTCUSDC"], 2, precision="bogus")


class TestReclaimedGauge:
    def test_export_beside_headroom(self):
        """Satellite (a): tickpath_overlap_reclaimed_seconds exports next
        to the headroom gauge, and the status block carries both ms
        quantile views — what the Grafana panel and recording rule read."""
        from ai_crypto_trader_tpu.obs.tickpath import TickPathScope
        from ai_crypto_trader_tpu.utils.metrics import MetricsRegistry

        m = MetricsRegistry()
        tp = TickPathScope(metrics=m)
        tp.observe_overlap(0.004)
        tp.observe_reclaimed(0.003)
        tp.observe_reclaimed(-1.0)                 # clamped, never negative
        tp.export()
        g = m.gauges
        assert g["crypto_trader_tpu_tickpath_overlap_headroom_seconds"] \
            == pytest.approx(0.004)
        assert g["crypto_trader_tpu_tickpath_overlap_reclaimed_seconds"] \
            == pytest.approx(0.003 / 2, abs=0.0016)   # p50 of {0.003, 0.0}
        st = tp.status()
        assert st["overlap_reclaimed_ms"]["p50"] >= 0.0
        assert st["overlap_reclaimed_ms"]["p99"] <= 3.1

    def test_coldstart_ledger_carries_cache_hits(self):
        from ai_crypto_trader_tpu.obs.tickpath import TickPathScope

        tp = TickPathScope()
        tp.record_cold_start("tick_engine", wall_s=1.0, compile_s=0.01,
                             compiles=1, cache_hits=3)
        entry = tp.coldstart_status()["programs"]["tick_engine"]
        assert entry["cache_hits"] == 3            # warm-replay evidence


class TestMicroBatching:
    def test_burst_coalesces_into_one_drain(self):
        """Satellite: queued frames coalesce into ONE fused dispatch —
        the burst publishes once per symbol, and the supervisor exports
        the coalescing counters."""
        from ai_crypto_trader_tpu.shell.stream import (MarketStream,
                                                       StreamSupervisor,
                                                       replay_frames)

        symbols = ("BTCUSDC", "ETHUSDC")
        ex = _exchange(symbols, n=900, advance=700)
        clock = {"t": 1_000_000.0}
        bus = EventBus()
        mon = MarketMonitor(bus, ex, symbols=list(symbols),
                            now_fn=lambda: clock["t"], kline_limit=LIMIT)
        st = MarketStream(mon, now_fn=lambda: clock["t"])
        frames = [json.dumps([{"e": "24hrMiniTicker", "s": s,
                               "c": "50000", "q": "1e6"}])
                  for s in symbols for _ in range(3)]
        published = asyncio.run(st.run(replay_frames(frames)))
        assert published >= len(symbols)
        # 6 frames arrived back-to-back: at least one drain coalesced
        assert st.micro_batches >= 1
        assert st.micro_batched_frames >= 2
        assert st.ticks_in == len(frames)
        from ai_crypto_trader_tpu.utils.metrics import MetricsRegistry

        m = MetricsRegistry()
        sup = StreamSupervisor(st, metrics=m, now_fn=lambda: clock["t"])
        sup.export()
        for name in ("stream_micro_batches_total",
                     "stream_micro_batched_frames_total"):
            assert any(name in k for k in m.counters), (name, m.counters)

    def test_microbatch_one_restores_frame_per_drain(self):
        """microbatch=1 is the strict compatibility mode: every frame
        drains alone (no coalescing counters move)."""
        from ai_crypto_trader_tpu.shell.stream import (MarketStream,
                                                       replay_frames)

        symbols = ("BTCUSDC",)
        ex = _exchange(symbols)
        clock = {"t": 1_000_000.0}
        mon = MarketMonitor(EventBus(), ex, symbols=list(symbols),
                            now_fn=lambda: clock["t"], kline_limit=LIMIT)
        st = MarketStream(mon, now_fn=lambda: clock["t"], microbatch=1)
        frames = [json.dumps([{"e": "24hrMiniTicker", "s": "BTCUSDC",
                               "c": "50000", "q": "1e6"}])
                  for _ in range(3)]
        asyncio.run(st.run(replay_frames(frames)))
        assert st.micro_batches == 0
        assert st.micro_batched_frames == 0


class TestAOTCache:
    def test_provenance_key_is_stable_and_coordinate_sensitive(self):
        a = aotcache.provenance_key({"jax_version": "1", "backend": "cpu",
                                     "device_kind": "x"})
        b = aotcache.provenance_key({"jax_version": "1", "backend": "cpu",
                                     "device_kind": "x"})
        c = aotcache.provenance_key({"jax_version": "2", "backend": "cpu",
                                     "device_kind": "x"})
        assert a == b and a != c and len(a) == 16

    def test_single_writer_lock_and_status(self, tmp_path):
        """Second opener runs UNCACHED (never half-cached); close()
        releases the lock for the next starter."""
        first = aotcache.AOTCache(str(tmp_path))
        try:
            assert first.enable({"jax_version": "1", "backend": "cpu",
                                 "device_kind": "x"})
            assert first.enabled and not first.warm
            st = first.status()
            assert st["enabled"] and st["key"] == first.key
            second = aotcache.AOTCache(str(tmp_path))
            assert not second.enable({"jax_version": "1", "backend": "cpu",
                                      "device_kind": "x"})
            assert "lock" in second.error
        finally:
            first.close()
        third = aotcache.AOTCache(str(tmp_path))
        try:
            assert third.enable({"jax_version": "1", "backend": "cpu",
                                 "device_kind": "x"})
            # bookkeeping files (meta.json, .writer.pid) are NOT cache
            # entries — an empty directory stays cold
            assert not third.warm
            (tmp_path / first.key / "exe.bin").write_bytes(b"x" * 10)
            fourth_status = third.status()
            assert fourth_status["entries"] == 1
        finally:
            third.close()
        fourth = aotcache.AOTCache(str(tmp_path))
        try:
            assert fourth.enable({"jax_version": "1", "backend": "cpu",
                                  "device_kind": "x"})
            assert fourth.warm                     # real entry → warm restart
            assert fourth.entries_at_enable == 1
        finally:
            fourth.close()

    def test_enable_failure_degrades_never_raises(self, tmp_path):
        """RESILIENCE row: an unusable cache root (here: the path is a
        FILE, so the provenance subdirectory cannot exist) is recorded on
        status() and the process runs uncached — no exception escapes."""
        root = tmp_path / "not_a_dir"
        root.write_text("occupied")
        c = aotcache.AOTCache(str(root))
        ok = c.enable({"jax_version": "1", "backend": "cpu",
                       "device_kind": "x"})
        assert not ok and c.error
        assert c.status()["enabled"] is False

    def test_prune_dir_bounds_oldest_first(self, tmp_path):
        for i in range(4):
            p = tmp_path / f"entry{i}"
            p.write_bytes(b"x" * 100)
            os.utime(p, (i, i))          # entry0 oldest
        (tmp_path / "meta.json").write_text("{}")   # never pruned
        removed = aotcache.prune_dir(str(tmp_path), 250)
        assert removed == 2
        assert not (tmp_path / "entry0").exists()
        assert not (tmp_path / "entry1").exists()
        assert (tmp_path / "entry3").exists()
        assert (tmp_path / "meta.json").exists()

    @pytest.mark.slow
    def test_fresh_subprocess_replays_compile(self, tmp_path):
        """Satellite (c): round-trip in a FRESH interpreter — the first
        child populates the provenance-keyed cache, the second REPLAYS
        (cache_hits > 0, compile collapses) instead of recompiling."""
        child = r"""
import json, os, sys, time
os.environ["JAX_PLATFORMS"] = "cpu"
import jax, jax.numpy as jnp
from ai_crypto_trader_tpu.utils.aotcache import AOTCache
from ai_crypto_trader_tpu.utils.tracing import JitCompileMonitor

mon = JitCompileMonitor.install()
c = AOTCache(sys.argv[1], min_compile_time_s=0.0)
assert c.enable({"jax_version": jax.__version__, "backend": "cpu",
                 "device_kind": "test"}), c.error
before = mon.sample()
# a shape/closure combination nothing else in the child compiles
f = jax.jit(lambda x: jnp.tanh(x @ x.T) * 2.719)
jax.block_until_ready(f(jnp.ones((33, 9))))
since = mon.since(before)
c.close()
print(json.dumps({"cache_hits": since["cache_hits"],
                  "warm": c.warm, "enabled": c.enabled}))
"""
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("JAX_COMPILATION_CACHE_DIR", None)

        def run():
            p = subprocess.run([sys.executable, "-c", child, str(tmp_path)],
                               capture_output=True, text=True, cwd=REPO,
                               env=env, timeout=180)
            assert p.returncode == 0, p.stderr[-800:]
            return json.loads(p.stdout.strip().splitlines()[-1])

        cold = run()
        assert cold["enabled"] and not cold["warm"]
        warm = run()
        assert warm["enabled"] and warm["warm"]
        assert warm["cache_hits"] >= 1, warm      # replayed, not recompiled
