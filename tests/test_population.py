"""Population-based RL (rl/population.py) — the ISSUE 19 contracts.

Tier-1 on a cheap indicator env (256 candles, tiny nets) so the whole
file compiles in seconds:

  * P=1 parity oracle: one-member PBT with an empty exploit bracket is
    BIT-identical to ``train_iterations`` on the same PRNGKey — hypers
    moved from compile-time constants to traced array content without
    perturbing a single bit of the training stream;
  * exchange determinism + exploit/explore semantics under a fixed key:
    bottom-quantile members copy a top-quantile donor's full training
    state, survivors pass through bitwise, perturbed hypers stay inside
    the search box;
  * the one-sync/zero-steady-recompile/donation contract of
    ``train_pbt`` (the evolve/ga.py contract, same observatories);
  * adoption: the winner registers and the scorecard gate decides
    active vs shadow on offline simulator fitness;
  * the env's new per-step trade cost: default bit-unchanged, scalar
    and per-scenario schedules charged on entry/exit, and the LOB
    scenario factory wires spread/2 into it.

The sharded-PBT case (8-device mesh ≡ single device, pad fraction
pinned) lives in tests/test_multichip.py with the other mesh dryruns.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from ai_crypto_trader_tpu import ops
from ai_crypto_trader_tpu.rl import (
    DQNConfig,
    dqn_init,
    make_env_params,
    train_iterations,
)
from ai_crypto_trader_tpu.rl import population as pop_mod
from ai_crypto_trader_tpu.rl.env import BUY, SELL, env_reset, env_step
from ai_crypto_trader_tpu.rl.population import (
    PBTConfig,
    _exchange_program,
    _program_pcfg,
    adopt_winner,
    best_params,
    pop_init,
    train_pbt,
)
from ai_crypto_trader_tpu.utils import devprof, meshprof

KEY = jax.random.PRNGKey(0)

# tiny everywhere: the contracts are structural, not statistical
CFG = DQNConfig(num_envs=2, rollout_len=2, hidden=(8,),
                replay_capacity=64, batch_size=8, learn_steps_per_iter=1,
                target_sync_every=3)


@pytest.fixture(scope="module")
def env(ohlcv):
    arrays = {k: jnp.asarray(v[:256]) for k, v in ohlcv.items()
              if k != "regime"}
    return make_env_params(ops.compute_indicators(arrays), episode_len=32)


def _leaves_equal(tree_a, tree_b):
    la, lb = jax.tree.leaves(tree_a), jax.tree.leaves(tree_b)
    assert len(la) == len(lb)
    return all(np.array_equal(np.asarray(a), np.asarray(b))
               for a, b in zip(la, lb))


class TestParityOracle:
    def test_pop_init_member_matches_dqn_init(self, env):
        pop = pop_init(KEY, env, CFG, PBTConfig(population=3))
        member_keys = jax.random.split(KEY, 3)
        for i in range(3):
            single = dqn_init(member_keys[i], env, CFG)
            member = jax.tree.map(lambda x: x[i], pop.members)
            assert _leaves_equal(member, single)

    def test_p1_pbt_bit_equals_train_iterations(self, env):
        """THE oracle: at P=1 the exploit bracket is empty, the exchange
        is a structural no-op, and G generations of ``iters`` iterations
        reproduce ``train_iterations(n_iters=G*iters)`` on the same key
        BIT-FOR-BIT — every DQNState leaf, replay ring included."""
        pcfg = PBTConfig(population=1, generations=2,
                         iters_per_generation=3, eval_steps=4)
        res = train_pbt(KEY, env, CFG, pcfg)

        single0 = dqn_init(jax.random.split(KEY, 1)[0], env, CFG)
        single, _ = train_iterations(env, single0, CFG, n_iters=6)

        member = jax.tree.map(lambda x: x[0], res.state.members)
        leaves_m = jax.tree.leaves(member)
        leaves_s = jax.tree.leaves(single)
        for a, b in zip(leaves_m, leaves_s):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        # hypers never perturbed: still exactly the config's f32 values
        assert float(res.state.hypers.learning_rate[0]) \
            == float(np.float32(CFG.learning_rate))
        assert int(res.state.hypers.target_sync_every[0]) \
            == CFG.target_sync_every
        # lineage recorded the no-op
        assert all(h["lineage"] == [0] for h in res.history)
        assert all(h["n_exploited"] == 0 for h in res.history)
        assert np.isfinite(res.fitness).all()


class TestExchange:
    PCFG = PBTConfig(population=8, generations=1, iters_per_generation=1,
                     eval_steps=4, exploit_frac=0.25)

    def _fresh(self, env):
        pop = pop_init(KEY, env, CFG, self.PCFG)
        # exchange donates members/hypers — hand it copies, keep the original
        return (jax.tree.map(jnp.array, pop.members),
                jax.tree.map(jnp.array, pop.hypers),
                jnp.array(pop.quarantined), jnp.array(pop.cooldown))

    def test_deterministic_under_fixed_key(self, env):
        ex = _exchange_program(CFG, _program_pcfg(self.PCFG))
        fitness = jnp.arange(8.0)
        k = jax.random.PRNGKey(3)
        m1, h1, q1, c1, lin1 = ex(*self._fresh(env), fitness, k)
        m2, h2, q2, c2, lin2 = ex(*self._fresh(env), fitness, k)
        assert _leaves_equal(m1, m2)
        assert _leaves_equal(h1, h2)
        np.testing.assert_array_equal(np.asarray(q1), np.asarray(q2))
        np.testing.assert_array_equal(np.asarray(c1), np.asarray(c2))
        np.testing.assert_array_equal(np.asarray(lin1), np.asarray(lin2))

    def test_exploit_explore_semantics(self, env):
        """fitness = arange → bottom bracket {0, 1}, top bracket {7, 6}.
        Clones carry the donor's entire training state with a forked key
        and in-box perturbed hypers; survivors pass through bitwise."""
        pop = pop_init(KEY, env, CFG, self.PCFG)
        ex = _exchange_program(CFG, _program_pcfg(self.PCFG))
        members, hypers, quarantined, _cooldown, lineage = ex(
            jax.tree.map(jnp.array, pop.members),
            jax.tree.map(jnp.array, pop.hypers),
            jnp.array(pop.quarantined), jnp.array(pop.cooldown),
            jnp.arange(8.0), jax.random.PRNGKey(3))
        assert not np.asarray(quarantined).any()   # a healthy fleet stays so
        lineage = np.asarray(lineage)
        pcfg = self.PCFG

        assert set(lineage[:2]) <= {6, 7}          # clones copy the top
        np.testing.assert_array_equal(lineage[2:], np.arange(2, 8))
        for i in (0, 1):
            donor = int(lineage[i])
            donor_params = jax.tree.map(lambda x: x[donor],
                                        pop.members.params)
            clone_params = jax.tree.map(lambda x, i=i: x[i], members.params)
            assert _leaves_equal(clone_params, donor_params)
            # …but never the donor's PRNG stream
            assert not np.array_equal(np.asarray(members.key[i]),
                                      np.asarray(pop.members.key[donor]))
            # jnp.clip clips to the bounds' f32 images — compare there
            def inside(v, lo_hi):
                lo, hi = (float(np.float32(b)) for b in lo_hi)
                return lo <= float(v) <= hi
            assert inside(hypers.learning_rate[i], pcfg.lr_bounds)
            assert inside(hypers.gamma[i], pcfg.gamma_bounds)
            assert inside(hypers.target_sync_every[i], pcfg.sync_bounds)
        # survivors: bitwise untouched, hypers included
        for i in range(2, 8):
            sm = jax.tree.map(lambda x, i=i: x[i], members)
            om = jax.tree.map(lambda x, i=i: x[i], pop.members)
            assert _leaves_equal(sm, om)
            sh = jax.tree.map(lambda x, i=i: x[i], hypers)
            oh = jax.tree.map(lambda x, i=i: x[i], pop.hypers)
            assert _leaves_equal(sh, oh)


class TestContracts:
    def test_one_sync_zero_recompile_donation(self, env, monkeypatch):
        """The evolve/ga.py regression guard, ported: ONE host_read per
        generation, a verified population-buffer donation on the first
        dispatch, and ZERO steady-state recompiles on a repeat run —
        the RecompileSentinel watches the same ``pbt_generation`` window
        the SteadyStateRecompile alert pages on (DEFAULT_HOT_PROGRAMS)."""
        cfg = CFG._replace(replay_capacity=48)     # fresh program cache key
        pcfg = PBTConfig(population=4, generations=2,
                         iters_per_generation=2, eval_steps=4)
        dp = devprof.DevProf()
        mp = meshprof.MeshProf()
        syncs = {"n": 0}
        real_read = pop_mod.host_read

        def counting_read(tree):
            syncs["n"] += 1
            return real_read(tree)

        monkeypatch.setattr(pop_mod, "host_read", counting_read)
        with devprof.use(dp), meshprof.use(mp):
            res = train_pbt(jax.random.PRNGKey(0), env, cfg, pcfg)
            assert syncs["n"] == pcfg.generations
            card = dp.cards["pbt_generation"]
            assert card.error is None
            assert card.flops > 0
            assert card.donation_ok is True        # no silent fleet copy
            assert mp.recompiles.steady_total() == 0

            res = train_pbt(jax.random.PRNGKey(1), env, cfg, pcfg)
            assert mp.recompiles.steady_total() == 0, \
                mp.recompiles.status()
            assert mp.recompiles.windows["pbt_generation"] \
                == 2 * pcfg.generations
            assert mp.transfers.total() == 0       # no unsanctioned pulls
            assert syncs["n"] == 2 * pcfg.generations
            assert mp.layouts["pbt_generation"].devices == 1
        assert len(res.history) == pcfg.generations
        assert np.isfinite(res.fitness).all()
        assert res.best_member == int(np.argmax(res.fitness))


class TestAdoption:
    @pytest.fixture(scope="class")
    def result(self, env):
        pcfg = PBTConfig(population=4, generations=1,
                         iters_per_generation=2, eval_steps=4)
        return train_pbt(jax.random.PRNGKey(5), env, CFG, pcfg)

    def test_winner_registers_active_without_incumbent(self, result,
                                                       tmp_path):
        from ai_crypto_trader_tpu.obs.scorecard import Scorecard
        from ai_crypto_trader_tpu.strategy.registry import ModelRegistry

        reg = ModelRegistry(path=str(tmp_path / "reg.json"))
        out = adopt_winner(result, reg, Scorecard())
        assert out["adopted"] is True
        assert out["reason"] == "incumbent_unscored"
        rec = reg.entries[out["version"]]
        assert rec["status"] == "active"
        assert rec["metadata"]["dynamics"] == "lob"
        assert rec["performance"]["fitness"] == out["fitness"]
        # the winner's params are extractable (hot-swap payload)
        p = best_params(result)
        assert jax.tree.leaves(p)[0].ndim >= 1

    def test_worse_candidate_lands_shadow(self, result, tmp_path):
        from ai_crypto_trader_tpu.obs.scorecard import Scorecard
        from ai_crypto_trader_tpu.strategy.registry import ModelRegistry

        reg = ModelRegistry(path=str(tmp_path / "reg.json"))
        # plant an incumbent with unbeatable offline fitness
        vid = reg.register("rl_policy", {"arch": "dqn_pbt", "fitness": 1e9},
                           metadata={"arch": "dqn_pbt"})
        reg.update_performance(vid, {"fitness": 1e9})
        reg.set_status(vid, "active")
        out = adopt_winner(result, reg, Scorecard())
        assert out["adopted"] is False
        assert "<=" in out["reason"]               # known-worse blocks
        rec = reg.entries[out["version"]]
        assert rec["status"] == "shadow"
        assert rec["metadata"]["adoption"] == "blocked_by_scorecard"


class TestTradeCost:
    def test_default_env_params_bit_unchanged(self, ohlcv):
        """No trade_cost argument → the scalar 0.0 python default, and
        stepping charges exactly the old fee path."""
        arrays = {k: jnp.asarray(v[:256]) for k, v in ohlcv.items()
                  if k != "regime"}
        ind = ops.compute_indicators(arrays)
        p = make_env_params(ind, episode_len=32)
        assert float(jnp.asarray(p.trade_cost)) == 0.0

    def test_scalar_trade_cost_charged_on_entry(self, ohlcv):
        arrays = {k: jnp.asarray(v[:256]) for k, v in ohlcv.items()
                  if k != "regime"}
        ind = ops.compute_indicators(arrays)
        p0 = make_env_params(ind, episode_len=32)
        p1 = make_env_params(ind, episode_len=32, trade_cost=0.002)
        s0, _ = env_reset(p0, KEY)
        s1, _ = env_reset(p1, KEY)
        _, _, r0, _ = env_step(p0, s0, jnp.asarray(BUY))
        _, _, r1, _ = env_step(p1, s1, jnp.asarray(BUY))
        np.testing.assert_allclose(float(r0) - float(r1), 0.002, rtol=1e-4)

    def test_per_step_schedule_indexed_by_time(self, ohlcv):
        """A [T] trade-cost schedule charges the cost at the STEP's
        time index — a spread blowout at t hits trades at t, not a flat
        average."""
        arrays = {k: jnp.asarray(v[:256]) for k, v in ohlcv.items()
                  if k != "regime"}
        ind = ops.compute_indicators(arrays)
        T = ind["close"].shape[0]
        p_flat = make_env_params(ind, episode_len=32, trade_cost=0.0)
        s, _ = env_reset(p_flat, KEY)
        t0 = int(s.t)
        sched = jnp.zeros(T).at[t0].set(0.004)
        p_spike = make_env_params(ind, episode_len=32, trade_cost=sched)
        s_sp, _ = env_reset(p_spike, KEY)
        assert int(s_sp.t) == t0                  # same reset offset
        _, _, r_flat, _ = env_step(p_flat, s, jnp.asarray(BUY))
        _, _, r_spike, _ = env_step(p_spike, s_sp, jnp.asarray(BUY))
        np.testing.assert_allclose(float(r_flat) - float(r_spike), 0.004,
                                   rtol=1e-4)
        # off the spike the schedule charges nothing extra
        s2, _, _, _ = env_step(p_spike, s_sp, jnp.asarray(BUY))
        _, _, r_exit, _ = env_step(p_spike, s2, jnp.asarray(SELL))
        s2f, _, _, _ = env_step(p_flat, s, jnp.asarray(BUY))
        _, _, r_exit_f, _ = env_step(p_flat, s2f, jnp.asarray(SELL))
        np.testing.assert_allclose(float(r_exit), float(r_exit_f),
                                   atol=1e-7)

    def test_lob_scenarios_wire_half_spread(self):
        """dynamics='lob' → trade_cost is the per-scenario half-spread
        schedule, so spread blowouts price entry/exit in the reward."""
        from ai_crypto_trader_tpu.sim.engine import scenario_env_params

        p, _labels = scenario_env_params(
            jax.random.PRNGKey(2), scenario="mixed", num_scenarios=2,
            steps=64, episode_len=16, dynamics="lob")
        tc = np.asarray(p.trade_cost)
        assert tc.ndim == 2 and tc.shape[0] == 2
        assert (tc >= 0).all() and tc.max() > 0
