"""Portfolio multi-symbol backtest + health/recovery utilities."""

import pytest
import numpy as np
import jax.numpy as jnp

from ai_crypto_trader_tpu.backtest.portfolio import (
    portfolio_backtest,
    stack_symbol_inputs,
)
from ai_crypto_trader_tpu.data.synthetic import generate_ohlcv
from ai_crypto_trader_tpu.utils.health import (
    HeartbeatRegistry,
    device_liveness,
    resume_or_init,
)


class TestPortfolio:
    def _per_symbol(self):
        return {f"S{i}USDC": {k: v for k, v in
                              generate_ohlcv(n=800 - 100 * i, seed=i).items()
                              if k != "regime"}
                for i in range(3)}

    @pytest.mark.slow
    def test_stack_pads_ragged(self):
        inputs, symbols = stack_symbol_inputs(self._per_symbol())
        assert symbols == ["S0USDC", "S1USDC", "S2USDC"]
        assert inputs.close.shape == (3, 800)     # padded to longest
        # left-padding repeats the first candle → flat prices
        np.testing.assert_allclose(np.asarray(inputs.close[2, :100]),
                                   np.asarray(inputs.close[2, 100]), rtol=1e-6)

    def test_portfolio_aggregates(self):
        inputs, symbols = stack_symbol_inputs(self._per_symbol())
        stats, metrics, portfolio = portfolio_backtest(inputs)
        assert stats.final_balance.shape == (3,)
        np.testing.assert_allclose(
            float(portfolio["total_final"]),
            float(np.asarray(stats.final_balance).sum()), rtol=1e-6)
        assert float(portfolio["total_initial"]) == 30_000.0
        assert np.isfinite(float(portfolio["mean_sharpe"]))


class TestBacktestQueue:
    @pytest.mark.slow
    def test_enqueue_process_results(self):
        import asyncio

        import jax.numpy as jnp

        from ai_crypto_trader_tpu.backtest.queue import BacktestQueue
        from ai_crypto_trader_tpu.shell.bus import EventBus

        async def go():
            bus = EventBus()
            q = BacktestQueue(bus=bus, now_fn=lambda: 0.0)
            sub = bus.subscribe("backtest_results")
            d = generate_ohlcv(n=300, seed=2)
            arrays = {k: jnp.asarray(v) for k, v in d.items() if k != "regime"}
            t1 = q.add_backtest_task(arrays)
            t2 = q.add_backtest_task(arrays, name="custom")
            assert q.pending == 2 and t2 == "custom"
            ran = await q.process_task_queue()
            assert ran == 2 and q.pending == 0
            assert "sharpe_ratio" in q.get_result(t1)["metrics"]
            assert sub.get_nowait()["data"]["id"] == t1
            # max_tasks cap respected
            q.add_backtest_task(arrays)
            q.add_backtest_task(arrays)
            assert await q.process_task_queue(max_tasks=1) == 1
            assert q.pending == 1
        asyncio.run(go())


class TestHealth:
    def test_heartbeats(self):
        clock = {"t": 0.0}
        hb = HeartbeatRegistry(stale_after_s=10, now_fn=lambda: clock["t"])
        hb.beat("monitor")
        hb.beat("executor")
        assert hb.stale() == []
        clock["t"] = 11.0
        hb.beat("executor")
        assert hb.stale() == ["monitor"]
        assert hb.health() == {"monitor": False, "executor": True}

    def test_device_liveness(self):
        out = device_liveness()
        assert out and all(out.values())

    def test_resume_or_init(self, tmp_path):
        from ai_crypto_trader_tpu.utils.checkpoint import save_checkpoint
        path = str(tmp_path / "ck")
        state, meta, resumed = resume_or_init(path, lambda: {"step": 0})
        assert not resumed and state == {"step": 0}
        save_checkpoint(path, {"step": np.asarray(7)}, {"note": "x"})
        state, meta, resumed = resume_or_init(path, lambda: {"step": 0})
        assert resumed and int(state["step"]) == 7 and meta["note"] == "x"
