"""Golden parity: the shared-capital portfolio replay vs a scalar Python
oracle of its contract — one balance, a global max_positions cap, symbols
processed in ascending index order within each candle (the semantics the
reference books through `backtesting/strategy_tester.py:225,314-369` and
config.json trading_params.max_positions)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ai_crypto_trader_tpu import ops
from ai_crypto_trader_tpu.backtest import (
    compute_metrics,
    default_params,
    portfolio_backtest,
    prepare_inputs,
    shared_capital_backtest,
)
from test_backtest_parity import python_position_size

# Slow tier (VERDICT r4 next#3): golden-parity / end-to-end /
# training / sharded-compile suite — deselected by the default
# run, executed via `pytest -m slow`.
pytestmark = pytest.mark.slow


# ---------------------------------------------------------------------------
# Scalar oracle (the contract in shared_capital_backtest's docstring)
# ---------------------------------------------------------------------------

def python_shared_backtest(close, signal, strength, vol, volume, conf,
                           decision, sl_series, tp_series,
                           initial=10_000.0, max_positions=5, warmup=10,
                           thresh=0.7, min_strength=70.0,
                           param_sl=None, param_tp=None,
                           equity_cadence="per_update"):
    S, T = close.shape
    balance = initial
    last_booked = initial
    in_pos = [False] * S
    entry = [0.0] * S
    qty = [0.0] * S
    sl = [0.0] * S
    tp = [0.0] * S
    max_eq, max_dd, max_dd_pct = initial, 0.0, 0.0
    trades = wins = 0
    tot_p = tot_l = 0.0
    returns = [0.0]
    cw = cl = mw = ml = 0
    sym_trades = [0] * S
    sym_pnl = [0.0] * S

    def close_pos(s, price):
        nonlocal balance, trades, wins, tot_p, tot_l, cw, cl, mw, ml
        pnl = (price - entry[s]) * qty[s]
        balance += pnl
        trades += 1
        sym_trades[s] += 1
        sym_pnl[s] += pnl
        if pnl > 0:
            wins += 1
            tot_p += pnl
            cw += 1; cl = 0
        else:
            tot_l -= pnl
            cl += 1; cw = 0
        mw, ml = max(mw, cw), max(ml, cl)
        in_pos[s] = False

    for t in range(T):
        if t < warmup:
            continue
        prev = balance
        for s in range(S):
            price = float(close[s, t])
            if in_pos[s]:
                pnl_pct = (price - entry[s]) / entry[s] * 100.0
                if pnl_pct <= -sl[s] or pnl_pct >= tp[s]:
                    close_pos(s, price)
            # the reference's per-update short-circuits (:220-225): no
            # booking when the symbol still holds or the slot cap binds
            if equity_cadence == "per_update":
                if in_pos[s] or sum(in_pos) >= max_positions:
                    continue
            n_open = sum(in_pos)
            if (not in_pos[s] and n_open < max_positions
                    and conf[s, t] >= thresh and strength[s, t] >= min_strength
                    and signal[s, t] == decision[s, t]
                    and decision[s, t] == 1):
                size, sl_frac, tp_frac = python_position_size(
                    balance, float(vol[s, t]), float(volume[s, t]))
                entry[s], qty[s] = price, size / price
                if param_sl is not None:
                    sl[s], tp[s] = param_sl, param_tp
                else:
                    sl[s], tp[s] = sl_frac * 100.0, tp_frac * 100.0
                if not np.isnan(sl_series[s, t]):
                    sl[s] = float(sl_series[s, t])
                if not np.isnan(tp_series[s, t]):
                    tp[s] = float(tp_series[s, t])
                in_pos[s] = True
            if equity_cadence == "per_update":
                # reference booking (:280-300), vs last BOOKED balance
                returns.append((balance - last_booked) / last_booked)
                last_booked = balance
                if balance > max_eq:
                    max_eq = balance
                dd = max_eq - balance
                if dd > max_dd:
                    max_dd, max_dd_pct = dd, dd / max_eq * 100.0
        if equity_cadence == "per_candle":
            returns.append((balance - prev) / prev)
            if balance > max_eq:
                max_eq = balance
            dd = max_eq - balance
            if dd > max_dd:
                max_dd, max_dd_pct = dd, dd / max_eq * 100.0
    for s in range(S):
        if in_pos[s]:
            close_pos(s, float(close[s, -1]))

    return dict(final_balance=balance, total_trades=trades,
                winning_trades=wins, total_profit=tot_p, total_loss=tot_l,
                max_drawdown=max_dd, max_drawdown_pct=max_dd_pct,
                n_r=len(returns), max_win_streak=mw, max_loss_streak=ml,
                sym_trades=sym_trades, sym_pnl=sym_pnl)


def _multi_inputs(n_symbols=4, n=700):
    from ai_crypto_trader_tpu.data.synthetic import generate_ohlcv

    per = []
    for s in range(n_symbols):
        d = generate_ohlcv(n=n, seed=100 + s)
        arrays = {k: jnp.asarray(v) for k, v in d.items() if k != "regime"}
        per.append(prepare_inputs(ops.compute_indicators(arrays)))
    return jax.tree.map(lambda *xs: jnp.stack(xs), *per)


@pytest.fixture(scope="module")
def minputs():
    return _multi_inputs()


class TestSharedCapitalParity:
    @pytest.mark.parametrize("cadence", ["per_update", "per_candle"])
    def test_vs_python_oracle(self, minputs, cadence):
        args = [np.asarray(x) for x in minputs]
        oracle = python_shared_backtest(*args, equity_cadence=cadence)
        assert oracle["total_trades"] > 0, "test vectors must actually trade"
        stats, per_symbol = shared_capital_backtest(minputs,
                                                    equity_cadence=cadence)
        assert int(stats.total_trades) == oracle["total_trades"]
        assert int(stats.winning_trades) == oracle["winning_trades"]
        assert int(stats.n_r) == oracle["n_r"]
        np.testing.assert_allclose(float(stats.final_balance),
                                   oracle["final_balance"], rtol=1e-4)
        np.testing.assert_allclose(float(stats.total_profit),
                                   oracle["total_profit"], rtol=1e-3, atol=1e-2)
        np.testing.assert_allclose(float(stats.total_loss),
                                   oracle["total_loss"], rtol=1e-3, atol=1e-2)
        np.testing.assert_allclose(float(stats.max_drawdown),
                                   oracle["max_drawdown"], rtol=1e-3, atol=1e-2)
        assert int(stats.max_win_streak) == oracle["max_win_streak"]
        assert int(stats.max_loss_streak) == oracle["max_loss_streak"]
        np.testing.assert_array_equal(np.asarray(per_symbol["trades"]),
                                      oracle["sym_trades"])
        np.testing.assert_allclose(np.asarray(per_symbol["realized_pnl"]),
                                   oracle["sym_pnl"], rtol=1e-3, atol=1e-2)

    def test_param_sl_tp_mode(self, minputs):
        p = default_params()
        args = [np.asarray(x) for x in minputs]
        oracle = python_shared_backtest(
            *args, param_sl=float(p.stop_loss), param_tp=float(p.take_profit))
        stats, _ = shared_capital_backtest(minputs, p, use_param_sl_tp=True)
        assert int(stats.total_trades) == oracle["total_trades"]
        np.testing.assert_allclose(float(stats.final_balance),
                                   oracle["final_balance"], rtol=1e-4)

    def test_position_cap_binds(self, minputs):
        """max_positions=1 must strictly reduce (or equal) trade count and
        change capital dynamics vs an uncapped run."""
        capped, _ = shared_capital_backtest(minputs, max_positions=1)
        S = minputs.close.shape[0]
        open_cap, _ = shared_capital_backtest(minputs, max_positions=S)
        assert int(capped.total_trades) <= int(open_cap.total_trades)
        args = [np.asarray(x) for x in minputs]
        oracle = python_shared_backtest(*args, max_positions=1)
        assert int(capped.total_trades) == oracle["total_trades"]
        np.testing.assert_allclose(float(capped.final_balance),
                                   oracle["final_balance"], rtol=1e-4)

    def test_capital_contention_differs_from_silos(self, minputs):
        """Shared pool ≠ independent silos: same TOTAL capitalization
        (portfolio_backtest scales the shared pool to per_symbol × S), but
        the capital models differ so the final balances must too."""
        silo_stats, _, _ = portfolio_backtest(
            minputs, initial_balance_per_symbol=2_500.0)
        shared, _, shared_port = portfolio_backtest(
            minputs, initial_balance_per_symbol=2_500.0, shared_capital=True)
        assert float(shared.initial_balance) == 10_000.0   # 2_500 × 4
        silo_total = float(jnp.sum(silo_stats.final_balance))
        assert abs(silo_total - float(shared.final_balance)) > 1e-3

    def test_vmap_over_population(self, minputs):
        from ai_crypto_trader_tpu.backtest import sample_params

        pop = sample_params(jax.random.PRNGKey(7), 4)
        fn = jax.vmap(lambda p: shared_capital_backtest(
            minputs, p, use_param_sl_tp=True)[0].final_balance)
        fb = fn(pop)
        assert fb.shape == (4,)
        single, _ = shared_capital_backtest(
            minputs, jax.tree.map(lambda x: x[2], pop), use_param_sl_tp=True)
        np.testing.assert_allclose(float(fb[2]), float(single.final_balance),
                                   rtol=1e-5)

    def test_metrics_pipeline(self, minputs):
        stats, _, port = portfolio_backtest(minputs, shared_capital=True)
        m = compute_metrics(stats)
        assert np.isfinite(float(m["sharpe_ratio"]))
        assert float(port["total_final"]) == pytest.approx(
            float(stats.final_balance))
