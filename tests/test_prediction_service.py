"""PredictionService cadence on a virtual clock.

Pins the reference's prediction-loop semantics
(`services/neural_network_service.py:1314-1480`): staleness-gated
re-predict per (symbol × interval), periodic retrain, HPO on request,
regime-tagged snapshots — all driven deterministically via now_fn.
"""

import asyncio
import glob
import os

import numpy as np
import pytest

from ai_crypto_trader_tpu.models.service import PredictionService
from ai_crypto_trader_tpu.shell.bus import EventBus

# Slow tier (VERDICT r4 next#3): golden-parity / end-to-end /
# training / sharded-compile suite — deselected by the default
# run, executed via `pytest -m slow`.
pytestmark = pytest.mark.slow


class Clock:
    def __init__(self, t0=1_000_000.0):
        self.t = t0

    def __call__(self):
        return self.t


def make_klines(n=160, seed=0):
    rng = np.random.default_rng(seed)
    close = 100.0 * np.cumprod(1 + rng.normal(0, 0.003, n))
    rows = []
    for i in range(n):
        c = close[i]
        rows.append([i * 60_000, c * 0.999, c * 1.002, c * 0.997, c,
                     1000.0 + rng.uniform(0, 10)])
    return rows


@pytest.fixture()
def svc(tmp_path):
    bus = EventBus()
    bus.set("historical_data_BTCUSDC_1m", make_klines())
    clock = Clock()
    svc = PredictionService(
        bus, ["BTCUSDC"], intervals=("1m",), now_fn=clock,
        seq_len=24, epochs=2, units=8, hpo_trials=2,
        checkpoint_dir=str(tmp_path))
    svc.clock = clock
    return svc


class TestCadence:
    def test_first_tick_trains_and_predicts(self, svc):
        out = asyncio.run(svc.run_once())
        assert out["trained"] == 1 and out["predicted"] == 1
        pred = svc.bus.get("nn_prediction_BTCUSDC_1m")
        assert pred["reference_time"] == svc.clock.t
        assert np.isfinite(pred["predicted_price"])
        assert 0.0 < pred["confidence"] <= 1.0
        assert svc.bus.published_counts.get("neural_network_predictions") == 1

    def test_staleness_gate_half_interval(self, svc):
        asyncio.run(svc.run_once())
        svc.clock.t += 29          # < 30 s = half of 1m: too fresh
        out = asyncio.run(svc.run_once())
        assert out["predicted"] == 0
        svc.clock.t += 2           # past the half-interval boundary
        out = asyncio.run(svc.run_once())
        assert out["predicted"] == 1

    def test_retrain_fires_every_24h(self, svc):
        asyncio.run(svc.run_once())
        assert svc.train_count == 1
        svc.clock.t += 86_399
        asyncio.run(svc.run_once())
        assert svc.train_count == 1      # not yet
        svc.clock.t += 2
        asyncio.run(svc.run_once())
        assert svc.train_count == 2      # 24 h elapsed → retrain

    def test_regime_tagged_snapshot(self, svc, tmp_path):
        svc.bus.set("market_regime", {"regime": "bull"})
        asyncio.run(svc.run_once())
        snaps = glob.glob(os.path.join(str(tmp_path), "*_bull.ckpt"))
        assert len(snaps) == 1

    def test_untagged_snapshot_without_regime(self, svc, tmp_path):
        asyncio.run(svc.run_once())
        snaps = os.listdir(str(tmp_path))
        assert any(s.endswith("_1m.ckpt") for s in snaps)

    def test_hpo_request_adopts_winner(self, svc):
        asyncio.run(svc.run_once())
        svc.bus.set("nn_optimization_request",
                    {"symbol": "BTCUSDC", "interval": "1m"})
        svc.clock.t += 40
        out = asyncio.run(svc.run_once())
        assert out["hpo"] == 1
        rec = svc.bus.get("nn_last_optimization_BTCUSDC_1m")
        assert rec["at"] == svc.clock.t
        assert "model_type" in rec["best"]
        assert svc.bus.get("nn_optimization_request") is None

    def test_per_pair_retrain_no_starvation(self, svc):
        # ETH has no data on the tick BTC trains; when its data arrives on
        # the next tick it must train immediately, not wait out the 24 h
        # global cadence
        svc.symbols = ["BTCUSDC", "ETHUSDC"]
        out = asyncio.run(svc.run_once())
        assert out["trained"] == 1          # only BTC has data
        svc.clock.t += 60
        svc.bus.set("historical_data_ETHUSDC_1m", make_klines(seed=1))
        out = asyncio.run(svc.run_once())
        assert out["trained"] == 1          # ETH trains now
        assert ("ETHUSDC", "1m") in svc.models

    def test_hpo_request_deferred_until_data(self, svc):
        asyncio.run(svc.run_once())
        svc.bus.set("nn_optimization_request",
                    {"symbol": "NODATAUSDC", "interval": "1m"})
        out = asyncio.run(svc.run_once())
        assert out["hpo"] == 0
        # request left pending for retry, not silently dropped
        assert svc.bus.get("nn_optimization_request") is not None
        svc.bus.set("historical_data_NODATAUSDC_1m", make_klines(seed=2))
        svc.symbols = ["BTCUSDC", "NODATAUSDC"]
        svc.clock.t += 60
        out = asyncio.run(svc.run_once())
        assert out["hpo"] == 1
        assert svc.bus.get("nn_optimization_request") is None

    def test_offload_mode_same_results(self, tmp_path):
        bus = EventBus()
        bus.set("historical_data_BTCUSDC_1m", make_klines())
        clock = Clock()
        svc = PredictionService(bus, ["BTCUSDC"], intervals=("1m",),
                                now_fn=clock, seq_len=24, epochs=2, units=8,
                                offload=True)
        out = asyncio.run(svc.run_once())
        assert out["trained"] == 1 and out["predicted"] == 1
        assert bus.get("nn_prediction_BTCUSDC_1m") is not None

    def test_no_data_no_crash(self):
        bus = EventBus()
        svc = PredictionService(bus, ["ETHUSDC"], intervals=("1m",),
                                now_fn=Clock(), seq_len=24, epochs=2)
        out = asyncio.run(svc.run_once())
        assert (out["predicted"], out["trained"], out["hpo"]) == (0, 0, 0)


class TestLauncherWiring:
    def test_extra_service_driven_by_tick(self, tmp_path):
        from ai_crypto_trader_tpu.data.synthetic import generate_ohlcv
        from ai_crypto_trader_tpu.data.ingest import from_dict
        from ai_crypto_trader_tpu.shell.exchange import FakeExchange
        from ai_crypto_trader_tpu.shell.launcher import TradingSystem

        clock = Clock()
        d = generate_ohlcv(n=2048, seed=3)
        series = from_dict({k: v for k, v in d.items() if k != "regime"},
                           symbol="BTCUSDC")
        ex = FakeExchange({"BTCUSDC": series}, quote_balance=10_000)
        ex.advance("BTCUSDC", steps=1500)   # enough history for the monitor
        sys_ = TradingSystem(ex, ["BTCUSDC"], now_fn=clock)
        svc = PredictionService(sys_.bus, ["BTCUSDC"], intervals=("1m",),
                                now_fn=clock, seq_len=24, epochs=2, units=8)
        sys_.extra_services.append(svc)

        asyncio.run(sys_.tick())
        assert svc.train_count == 1 and svc.predict_count == 1
        assert sys_.bus.get("nn_prediction_BTCUSDC_1m") is not None
        assert "nn" in sys_.heartbeats.health()
