"""Crash-safe trading state: write-ahead journaling in the executor,
restart reconciliation against exchange ground truth, the supervised tick
loop's crash-loop breaker, and the robustness satellites (health expect,
bus overflow policy, bounded resilient-exchange blocking)."""

import asyncio

import numpy as np
import pytest

from ai_crypto_trader_tpu.config import TradingParams
from ai_crypto_trader_tpu.data.ingest import from_dict
from ai_crypto_trader_tpu.shell.bus import EventBus
from ai_crypto_trader_tpu.shell.exchange import (
    ExchangeUnavailable,
    FakeExchange,
    ResilientExchange,
)
from ai_crypto_trader_tpu.shell.executor import TradeExecutor
from ai_crypto_trader_tpu.utils.journal import WriteAheadJournal

SYMBOL = "BTCUSDC"


def flat_series(n=400, price=100.0, drop_at=None, drop_to=None,
                rise_at=None, rise_to=None):
    """Deterministic price path: flat, with an optional step down/up —
    exact control over whether a stop or take-profit fills."""
    close = np.full(n, price, np.float64)
    if drop_at is not None:
        close[drop_at:] = drop_to
    if rise_at is not None:
        close[rise_at:] = rise_to
    return from_dict({"open": close, "high": close * 1.0005,
                      "low": close * 0.9995, "close": close,
                      "volume": np.full(n, 1000.0)}, symbol=SYMBOL)


PERMISSIVE = TradingParams(ai_confidence_threshold=0.0,
                           min_signal_strength=0.0, min_trade_amount=1.0)


def signal(price):
    return {"symbol": SYMBOL, "signal": "BUY", "decision": "BUY",
            "confidence": 1.0, "signal_strength": 100.0,
            "current_price": price, "volatility": 0.015,
            "avg_volume": 60_000.0}


def make_executor(ex, tmp_path, clock=None, journal=True):
    import time as _time

    now = (lambda: clock["t"]) if clock else _time.time
    j = (WriteAheadJournal(str(tmp_path / "trades.journal"), now_fn=now)
         if journal else None)
    return TradeExecutor(EventBus(now_fn=now), ex, trading=PERMISSIVE,
                         journal=j, now_fn=now)


async def open_trade(execu, ex):
    price = ex.get_ticker(SYMBOL)["price"]
    trade = await execu.handle_signal(signal(price))
    assert trade is not None
    assert trade.stop_order_id is not None and trade.tp_order_id is not None
    return trade


def restart(ex, tmp_path, clock=None):
    """A 'new process': fresh executor with cold books over the same
    journal file and the same venue."""
    fresh = make_executor(ex, tmp_path, clock=clock)
    report = asyncio.run(fresh.recover_from_journal())
    return fresh, report


class TestRecoveryMatrix:
    """position open/closed × protective order live/filled/missing."""

    def test_live_protection_readopted_not_replaced(self, tmp_path):
        ex = FakeExchange({SYMBOL: flat_series()}, quote_balance=10_000.0)
        ex.advance(steps=50)
        execu = make_executor(ex, tmp_path)
        trade = asyncio.run(open_trade(execu, ex))
        execu.journal.simulate_crash()            # die between fsyncs

        fresh, report = restart(ex, tmp_path)
        assert SYMBOL in fresh.active_trades
        t = fresh.active_trades[SYMBOL]
        # the SAME venue orders were adopted — nothing cancelled, nothing
        # double-placed
        assert t.stop_order_id == trade.stop_order_id
        assert t.tp_order_id == trade.tp_order_id
        assert len(ex.open_orders) == 2
        assert report["finalized_while_down"] == 0
        assert report["orphans_cancelled"] == 0

    def test_stop_filled_while_down_finalizes_and_cancels_sibling(
            self, tmp_path):
        ex = FakeExchange({SYMBOL: flat_series(drop_at=60, drop_to=90.0)},
                          quote_balance=10_000.0, fee_rate=0.0)
        ex.advance(steps=50)
        execu = make_executor(ex, tmp_path)
        asyncio.run(open_trade(execu, ex))
        execu.journal.flush()
        ex.advance(steps=15)                      # price gaps through stop

        fresh, report = restart(ex, tmp_path)
        assert SYMBOL not in fresh.active_trades
        assert report["finalized_while_down"] == 1
        assert len(fresh.closed_trades) == 1
        assert "Stop Loss" in fresh.closed_trades[0]["reason"]
        assert ex.open_orders == {}               # TP sibling cancelled
        # inventory really left the account at the stop fill
        assert ex.get_balances().get("BTC", 0.0) == pytest.approx(0.0)

    def test_tp_filled_while_down_finalizes_with_profit(self, tmp_path):
        ex = FakeExchange({SYMBOL: flat_series(rise_at=60, rise_to=115.0)},
                          quote_balance=10_000.0, fee_rate=0.0)
        ex.advance(steps=50)
        execu = make_executor(ex, tmp_path)
        asyncio.run(open_trade(execu, ex))
        execu.journal.flush()
        ex.advance(steps=15)                      # price gaps through TP

        fresh, report = restart(ex, tmp_path)
        assert SYMBOL not in fresh.active_trades
        assert report["finalized_while_down"] == 1
        assert "Take Profit" in fresh.closed_trades[0]["reason"]
        assert fresh.closed_trades[0]["pnl"] > 0
        assert ex.open_orders == {}

    def test_missing_protection_replaced_on_recovery(self, tmp_path):
        ex = FakeExchange({SYMBOL: flat_series()}, quote_balance=10_000.0)
        ex.advance(steps=50)
        execu = make_executor(ex, tmp_path)
        trade = asyncio.run(open_trade(execu, ex))
        execu.journal.flush()
        # the venue cancelled both legs while we were down (e.g. symbol
        # maintenance) — recovery must re-protect the naked position
        ex.cancel_order(SYMBOL, trade.stop_order_id)
        ex.cancel_order(SYMBOL, trade.tp_order_id)

        fresh, report = restart(ex, tmp_path)
        t = fresh.active_trades[SYMBOL]
        assert report["repaired_protection"] == 1
        assert t.stop_order_id is not None and t.tp_order_id is not None
        assert ex.order_is_open(SYMBOL, t.stop_order_id)
        assert ex.order_is_open(SYMBOL, t.tp_order_id)

    def test_unacked_protection_adopted_by_client_id(self, tmp_path):
        """Crash AFTER the stop/TP placements landed but BEFORE their acks
        were fsynced: recovery must adopt the live venue orders via the
        journaled intent client ids, not place a second pair."""
        ex = FakeExchange({SYMBOL: flat_series()}, quote_balance=10_000.0)
        ex.advance(steps=50)
        # fsync_every=1 would persist acks; recreate the executor with a
        # large batch so ONLY flush=True records (intents) survive
        execu = make_executor(ex, tmp_path)
        execu.journal.fsync_every = 10 ** 9
        trade = asyncio.run(open_trade(execu, ex))
        execu.journal.simulate_crash()            # protect_acks lost

        fresh, report = restart(ex, tmp_path)
        t = fresh.active_trades[SYMBOL]
        assert t.stop_order_id == trade.stop_order_id
        assert t.tp_order_id == trade.tp_order_id
        assert len(ex.open_orders) == 2           # no duplicate protection

    def test_closed_ledger_conserved_across_restart(self, tmp_path):
        ex = FakeExchange({SYMBOL: flat_series()}, quote_balance=10_000.0,
                          fee_rate=0.0)
        ex.advance(steps=50)
        execu = make_executor(ex, tmp_path)

        async def trade_twice():
            for _ in range(2):
                await open_trade(execu, ex)
                ex.advance()
                price = ex.get_ticker(SYMBOL)["price"]
                await execu.close_trade(SYMBOL, price, "Manual")

        asyncio.run(trade_twice())
        closed_before = list(execu.closed_trades)
        execu.journal.simulate_crash()

        fresh, _ = restart(ex, tmp_path)
        assert len(fresh.closed_trades) == len(closed_before) == 2
        for a, b in zip(fresh.closed_trades, closed_before):
            assert a["pnl"] == pytest.approx(b["pnl"])
            assert a["symbol"] == b["symbol"]
        # and a restart-of-the-restart replays from the compacted snapshot
        fresh2, report2 = restart(ex, tmp_path)
        assert len(fresh2.closed_trades) == 2
        assert report2["journal"]["replayed"] >= 1    # snapshot record


class TestAmbiguousEntry:
    """The client_order_id satellite: 'place_order raised — did it reach
    the exchange?' must resolve by deterministic client id."""

    def _flaky_entry(self, ex, fail_mode):
        real = ex.place_order
        state = {"armed": True}

        def place(symbol, side, order_type, quantity, price=None,
                  stop_price=None, client_order_id=None):
            if state["armed"] and order_type == "MARKET" and side == "BUY":
                state["armed"] = False
                if fail_mode == "after":
                    real(symbol, side, order_type, quantity, price,
                         stop_price, client_order_id=client_order_id)
                raise ConnectionError("mid-flight failure")
            return real(symbol, side, order_type, quantity, price,
                        stop_price, client_order_id=client_order_id)

        ex.place_order = place
        return state

    def _resilient(self, ex):
        clock = {"t": 0.0}
        return ResilientExchange(
            ex, now_fn=lambda: clock["t"],
            sleep=lambda s: clock.__setitem__("t", clock["t"] + s),
            max_read_retries=0, failure_threshold=100)

    def test_order_that_landed_is_adopted_not_doubled(self, tmp_path):
        inner = FakeExchange({SYMBOL: flat_series()}, quote_balance=10_000.0)
        inner.advance(steps=50)
        self._flaky_entry(inner, "after")         # reached venue, then raised
        ex = self._resilient(inner)
        execu = make_executor(ex, tmp_path)

        async def go():
            with pytest.raises(ExchangeUnavailable):
                await execu.handle_signal(signal(100.0))
            assert execu.active_trades == {}
            assert len(execu.pending_intents) == 1
            # entry for the symbol is blocked while the intent is unresolved
            assert not execu.should_execute(signal(100.0))
            # venue answers again → the landed order is ADOPTED
            await execu.resolve_pending_intents()
            assert SYMBOL in execu.active_trades
            assert execu.pending_intents == {}
            # exactly ONE entry fill on the venue — no double order
            buys = [f for f in inner.fills if f["side"] == "BUY"]
            assert len(buys) == 1

        asyncio.run(go())

    def test_order_that_never_arrived_is_discarded(self, tmp_path):
        inner = FakeExchange({SYMBOL: flat_series()}, quote_balance=10_000.0)
        inner.advance(steps=50)
        self._flaky_entry(inner, "before")        # lost before the venue
        ex = self._resilient(inner)
        execu = make_executor(ex, tmp_path)

        async def go():
            with pytest.raises(ExchangeUnavailable):
                await execu.handle_signal(signal(100.0))
            await execu.resolve_pending_intents()
            assert execu.active_trades == {}
            assert execu.pending_intents == {}
            assert inner.fills == []
            # re-entry unblocked: the next signal trades normally
            t = await execu.handle_signal(signal(100.0))
            assert t is not None

        asyncio.run(go())

    def test_ambiguous_entry_resolved_across_restart(self, tmp_path):
        """The full crash variant: the process dies with the intent
        journaled but unresolved; the restarted process adopts the
        position instead of double-entering."""
        inner = FakeExchange({SYMBOL: flat_series()}, quote_balance=10_000.0)
        inner.advance(steps=50)
        self._flaky_entry(inner, "after")
        ex = self._resilient(inner)
        execu = make_executor(ex, tmp_path)

        async def go():
            with pytest.raises(ExchangeUnavailable):
                await execu.handle_signal(signal(100.0))

        asyncio.run(go())
        execu.journal.simulate_crash()

        fresh = make_executor(ex, tmp_path)
        report = asyncio.run(fresh.recover_from_journal())
        assert report["adopted"] == 1
        assert SYMBOL in fresh.active_trades
        t = fresh.active_trades[SYMBOL]
        assert t.stop_order_id is not None        # protection placed too
        assert len([f for f in inner.fills if f["side"] == "BUY"]) == 1

    def test_orphan_protective_order_cancelled(self, tmp_path):
        """A protective order whose parent position is gone (books lost
        the closure, position sold) must be swept, not left to fire."""
        ex = FakeExchange({SYMBOL: flat_series()}, quote_balance=10_000.0,
                          fee_rate=0.0)
        ex.advance(steps=50)
        execu = make_executor(ex, tmp_path)
        trade = asyncio.run(open_trade(execu, ex))
        execu.journal.flush()
        # the position is sold out-of-band (another process / manual) and
        # its closure never reached our journal; one leg also got cancelled
        ex.cancel_order(SYMBOL, trade.tp_order_id)
        ex.balances["BTC"] = 0.0
        # journal still believes the trade is open with a live stop order.
        # Simulate losing the books AND the position record: replay from a
        # journal whose entry_ack exists but whose trade will reconcile
        # against a venue that has no inventory — the stop order must not
        # survive as an orphan once the trade finalizes via fill-less stop.
        # Deterministic variant: drop the active trade by journaling the
        # closure, leaving the stop order resting.
        execu.journal.append("trade_closed", {
            "symbol": SYMBOL, "entry_price": trade.entry_price,
            "exit_price": trade.entry_price, "quantity": trade.quantity,
            "pnl": 0.0, "reason": "OOB", "opened_at": trade.opened_at,
            "closed_at": 0.0}, flush=True)

        fresh, report = restart(ex, tmp_path)
        assert SYMBOL not in fresh.active_trades
        assert report["orphans_cancelled"] == 1
        assert ex.open_orders == {}               # stop is gone


class TestFakeExchangeClientIds:
    def test_client_id_is_idempotency_key(self):
        ex = FakeExchange({SYMBOL: flat_series()}, quote_balance=10_000.0)
        ex.advance(steps=10)
        a = ex.place_order(SYMBOL, "BUY", "MARKET", 1.0,
                           client_order_id="wj-ent-1")
        b = ex.place_order(SYMBOL, "BUY", "MARKET", 1.0,
                           client_order_id="wj-ent-1")
        assert b.get("duplicate") is True
        assert b["order_id"] == a["order_id"]
        assert len([f for f in ex.fills if f["side"] == "BUY"]) == 1

    def test_find_order_by_client_id_open_and_filled(self):
        ex = FakeExchange({SYMBOL: flat_series()}, quote_balance=10_000.0)
        ex.advance(steps=10)
        ex.place_order(SYMBOL, "BUY", "MARKET", 1.0, client_order_id="m1")
        found = ex.find_order_by_client_id(SYMBOL, "m1")
        assert found["status"] == "FILLED"
        lim = ex.place_order(SYMBOL, "SELL", "LIMIT", 1.0, price=150.0,
                             client_order_id="l1")
        found = ex.find_order_by_client_id(SYMBOL, "l1")
        assert found["status"] == "OPEN"
        assert found["order_id"] == lim["order_id"]
        assert ex.find_order_by_client_id(SYMBOL, "nope") is None
        assert any(o["client_order_id"] == "l1"
                   for o in ex.list_open_orders(SYMBOL))


class TestStageSupervision:
    """A non-ExchangeUnavailable exception inside one stage must never
    kill run(): backoff → quarantine → ServiceCrashLoop, rest alive."""

    def _system(self, clock):
        from ai_crypto_trader_tpu.shell.launcher import TradingSystem

        ex = FakeExchange({SYMBOL: flat_series(n=900)},
                          quote_balance=10_000.0)
        ex.advance(steps=600)
        return ex, TradingSystem(ex, [SYMBOL], now_fn=lambda: clock["t"],
                                 stage_max_failures=3, stage_backoff_s=0.0,
                                 stage_quarantine_s=600.0)

    def _drive(self, system, ex, clock, ticks):
        async def go():
            out = []
            for _ in range(ticks):
                ex.advance()
                clock["t"] += 60.0
                out.append(await system.tick())
            return out

        return asyncio.run(go())

    def test_crash_looping_analyzer_is_quarantined_not_fatal(self):
        clock = {"t": 0.0}
        ex, system = self._system(clock)
        q_alerts = system.bus.subscribe("alerts")

        async def poisoned():
            raise ValueError("poisoned payload")

        system.analyzer.run_once = poisoned
        results = self._drive(system, ex, clock, 6)   # would previously raise

        br = system.stage_breakers["analyzer"]
        assert br.quarantined
        assert br.failures == 3                   # N consecutive → quarantine
        alerts = []
        while not q_alerts.empty():
            alerts.append(q_alerts.get_nowait()["data"])
        names = [a["name"] for a in alerts]
        assert "StageError" in names
        # the edge-triggered publish names the stage and fires exactly once
        # (the rule engine additionally raises its own state alert)
        crash = [a for a in alerts if a["name"] == "ServiceCrashLoop"
                 and a.get("service") == "analyzer"]
        assert len(crash) == 1
        # the OTHER stages kept ticking the whole time
        assert all(r["published"] > 0 for r in results)
        assert clock["t"] - system.heartbeats.beats["monitor"] <= 60.0
        assert clock["t"] - system.heartbeats.beats["executor"] <= 60.0
        # the quarantined stage's heartbeat went stale -> unhealthy
        assert system.heartbeats.health()["analyzer"] is False
        # and the rule-engine alert reflects the quarantine state
        assert "ServiceCrashLoop" in system.alerts.active

    def test_each_core_stage_is_isolated(self):
        for stage_attr, fn_name in (("monitor", "poll"),
                                    ("analyzer", "run_once"),
                                    ("executor", "run_once")):
            clock = {"t": 0.0}
            ex, system = self._system(clock)

            async def boom(*a, **kw):
                raise RuntimeError("injected")

            setattr(getattr(system, stage_attr), fn_name, boom)
            results = self._drive(system, ex, clock, 5)
            assert len(results) == 5              # run() never died
            assert system.stage_breakers[stage_attr].quarantined

    def test_quarantine_probe_recovers_the_stage(self):
        clock = {"t": 0.0}
        ex, system = self._system(clock)
        fail = {"on": True}
        real = system.analyzer.run_once

        async def flaky():
            if fail["on"]:
                raise ValueError("still broken")
            return await real()

        system.analyzer.run_once = flaky
        self._drive(system, ex, clock, 4)
        assert system.stage_breakers["analyzer"].quarantined

        fail["on"] = False
        self._drive(system, ex, clock, 2)         # still inside quarantine
        assert system.stage_breakers["analyzer"].quarantined

        clock["t"] += 700.0                       # past quarantine_s: probe
        self._drive(system, ex, clock, 2)
        br = system.stage_breakers["analyzer"]
        assert not br.quarantined
        assert br.failures == 0
        assert clock["t"] - system.heartbeats.beats["analyzer"] <= 60.0

    def test_exchange_unavailable_keeps_skip_tick_semantics(self):
        clock = {"t": 0.0}
        ex, system = self._system(clock)

        async def down():
            raise ExchangeUnavailable("circuit open")

        system.monitor.poll = down
        results = self._drive(system, ex, clock, 2)
        assert all("skipped" in r for r in results)
        # an outage is NOT a stage crash: no quarantine accounting
        assert system.stage_breakers["monitor"].failures == 0


class TestHealthExpect:
    def test_never_beaten_expected_service_reports_unhealthy(self):
        from ai_crypto_trader_tpu.utils.health import HeartbeatRegistry

        clock = {"t": 0.0}
        reg = HeartbeatRegistry(stale_after_s=30.0, now_fn=lambda: clock["t"])
        reg.expect("analyzer")
        reg.beat("monitor")
        assert reg.health() == {"monitor": True, "analyzer": True}  # grace
        clock["t"] = 31.0
        health = reg.health()
        assert health["analyzer"] is False        # never beat → unhealthy
        assert health["monitor"] is False         # stale the usual way
        reg.beat("analyzer")
        clock["t"] = 40.0
        assert reg.health()["analyzer"] is True

    def test_launcher_and_stack_register_expected_services(self, tmp_path):
        from ai_crypto_trader_tpu.shell.launcher import TradingSystem

        clock = {"t": 0.0}
        ex = FakeExchange({SYMBOL: flat_series(n=900)},
                          quote_balance=10_000.0)
        ex.advance(steps=600)
        system = TradingSystem(ex, [SYMBOL], now_fn=lambda: clock["t"])
        assert {"monitor", "analyzer", "executor"} <= set(
            system.heartbeats.expected)

    def test_servicedown_fires_for_stage_that_never_beats(self):
        from ai_crypto_trader_tpu.shell.launcher import TradingSystem

        clock = {"t": 0.0}
        ex = FakeExchange({SYMBOL: flat_series(n=900)},
                          quote_balance=10_000.0)
        ex.advance(steps=600)
        system = TradingSystem(ex, [SYMBOL], now_fn=lambda: clock["t"],
                               stage_max_failures=2, stage_backoff_s=0.0)

        async def boom():
            raise RuntimeError("dead on arrival")

        system.analyzer.run_once = boom

        async def go():
            for _ in range(3):
                ex.advance()
                clock["t"] += 60.0
                await system.tick()

        asyncio.run(go())
        # analyzer never beat once, yet ServiceDown fired for it
        assert "analyzer" not in system.heartbeats.beats
        assert system.heartbeats.health()["analyzer"] is False
        assert "ServiceDown" in system.alerts.active


class TestBusOverflowPolicy:
    def test_critical_channels_grow_instead_of_dropping(self):
        async def go():
            bus = EventBus(max_queue=4)
            q_alerts = bus.subscribe("alerts")
            q_signals = bus.subscribe("trading_signals")
            q_bulk = bus.subscribe("market_updates")
            for i in range(10):
                await bus.publish("alerts", {"i": i})
                await bus.publish("trading_signals", {"i": i})
                await bus.publish("market_updates", {"i": i})
            # critical channels: every message retained
            assert q_alerts.qsize() == 10
            assert q_signals.qsize() == 10
            assert bus.dropped_counts["alerts"] == 0
            assert bus.dropped_counts["trading_signals"] == 0
            # bulk telemetry: bounded, oldest dropped
            assert q_bulk.qsize() == 4
            assert bus.dropped_counts["market_updates"] == 6
            assert q_bulk.get_nowait()["data"]["i"] == 6   # oldest kept = 6

        asyncio.run(go())

    def test_alert_on_drop_policy_publishes_message_loss(self):
        async def go():
            bus = EventBus(max_queue=2,
                           overflow={"pattern_signals": "alert_on_drop"})
            q_alerts = bus.subscribe("alerts")
            bus.subscribe("pattern_signals")
            for i in range(5):
                await bus.publish("pattern_signals", {"i": i})
            losses = []
            while not q_alerts.empty():
                msg = q_alerts.get_nowait()["data"]
                if msg["name"] == "MessageLoss":
                    losses.append(msg)
            assert losses and losses[0]["channel"] == "pattern_signals"

        asyncio.run(go())


class TestBlockingBudget:
    """ResilientExchange satellite: a retry storm must not freeze the
    shared event loop for unbounded wall-clock."""

    class _Clock:
        def __init__(self):
            self.t, self.sleeps = 0.0, []

        def now(self):
            return self.t

        def sleep(self, dt):
            self.sleeps.append(dt)
            self.t += dt

    def test_total_blocking_per_call_is_bounded(self):
        clock = self._Clock()

        class Dead(FakeExchange):
            def get_ticker(self, symbol):
                raise ConnectionError("down")

        ex = ResilientExchange(
            Dead({SYMBOL: flat_series()}), now_fn=clock.now,
            sleep=clock.sleep, max_read_retries=8, base_delay_s=10.0,
            max_delay_s=100.0, failure_threshold=100, max_block_s=15.0)
        with pytest.raises(ExchangeUnavailable):
            ex.get_ticker(SYMBOL)
        assert sum(clock.sleeps) <= 15.0          # storm cut off at budget
        assert ex.breaker.failures == 1           # still counts as failure

    def test_rate_limit_deficit_respects_budget(self):
        clock = self._Clock()
        inner = FakeExchange({SYMBOL: flat_series()})
        inner.advance(steps=5)
        ex = ResilientExchange(inner, now_fn=clock.now, sleep=clock.sleep,
                               rate_per_s=0.001, burst=1.0, max_block_s=5.0)
        ex.get_ticker(SYMBOL)                     # consumes the burst
        with pytest.raises(ExchangeUnavailable):
            ex.get_ticker(SYMBOL)                 # deficit ≈ 1000s >> budget
        assert sum(clock.sleeps) <= 5.0

    def test_unbounded_mode_preserves_old_behavior(self):
        clock = self._Clock()
        inner = FakeExchange({SYMBOL: flat_series()})
        inner.advance(steps=5)
        ex = ResilientExchange(inner, now_fn=clock.now, sleep=clock.sleep,
                               rate_per_s=0.1, burst=1.0, max_block_s=None)
        ex.get_ticker(SYMBOL)
        ex.get_ticker(SYMBOL)                     # sleeps out the deficit
        assert sum(clock.sleeps) >= 9.0

    def test_acall_runs_protected_call_off_loop(self):
        inner = FakeExchange({SYMBOL: flat_series()})
        inner.advance(steps=5)
        ex = ResilientExchange(inner)

        async def go():
            out = await ex.acall("get_ticker", SYMBOL)
            assert out["price"] > 0

        asyncio.run(go())


class TestReviewHardening:
    """Regressions for the review findings on the reconciliation path."""

    def test_live_venue_order_keeps_intent_parked(self, tmp_path):
        """An intent whose venue order is still OPEN/NEW must stay parked
        (entry blocked) — neither adopted nor discarded."""
        ex = FakeExchange({SYMBOL: flat_series()}, quote_balance=10_000.0)
        ex.advance(steps=50)
        execu = make_executor(ex, tmp_path)
        coid = "wj-ent-BTCUSDC-9"
        # the ambiguous order actually landed as a LIVE resting order
        ex.place_order(SYMBOL, "BUY", "LIMIT", 1.0, price=90.0,
                       client_order_id=coid)
        execu.pending_intents[coid] = {
            "phase": "entry", "symbol": SYMBOL, "client_order_id": coid,
            "quantity": 1.0, "sl_pct": 2.0, "tp_pct": 4.0}

        out = asyncio.run(execu.resolve_pending_intents())
        assert out == {"adopted": 0, "discarded": 0, "finalized": 0}
        assert coid in execu.pending_intents          # still parked
        assert not execu.should_execute(signal(100.0))  # entry still blocked

    def test_zero_price_resolution_falls_back_to_market(self, tmp_path):
        """Venues report price=0 for MARKET orders; adoption must never
        book an entry at 0 (poisoned trailing stop / TP / PnL)."""
        ex = FakeExchange({SYMBOL: flat_series()}, quote_balance=10_000.0)
        ex.advance(steps=50)
        execu = make_executor(ex, tmp_path)
        coid = "wj-ent-BTCUSDC-3"
        ex.place_order(SYMBOL, "BUY", "MARKET", 1.0, client_order_id=coid)
        real_find = ex.find_order_by_client_id

        def find(symbol, client_order_id):
            out = real_find(symbol, client_order_id)
            if out is not None:
                out["price"] = 0.0                  # Binance MARKET quirk
            return out

        ex.find_order_by_client_id = find
        execu.pending_intents[coid] = {
            "phase": "entry", "symbol": SYMBOL, "client_order_id": coid,
            "quantity": 1.0, "sl_pct": 2.0, "tp_pct": 4.0}
        out = asyncio.run(execu.resolve_pending_intents())
        assert out["adopted"] == 1
        t = execu.active_trades[SYMBOL]
        assert t.entry_price == pytest.approx(
            ex.get_ticker(SYMBOL)["price"])           # not 0

    def test_snapshot_rotation_conserves_closed_aggregates(self, tmp_path):
        ex = FakeExchange({SYMBOL: flat_series()}, quote_balance=50_000.0,
                          fee_rate=0.0)
        ex.advance(steps=50)
        execu = make_executor(ex, tmp_path)
        execu.SNAPSHOT_CLOSED_TAIL = 2                # force rotation

        async def churn():
            for _ in range(5):
                await open_trade(execu, ex)
                ex.advance()
                await execu.close_trade(
                    SYMBOL, ex.get_ticker(SYMBOL)["price"], "Manual")

        asyncio.run(churn())
        total_n = execu.closed_count()
        total_pnl = execu.closed_pnl()
        assert total_n == 5
        execu.journal.compact(execu.snapshot_state())
        execu.journal.close()

        fresh = make_executor(ex, tmp_path)
        asyncio.run(fresh.recover_from_journal())
        # per-record tail is bounded, but the ledger TOTALS survive
        assert len(fresh.closed_trades) == 2
        assert fresh.closed_count() == total_n
        assert fresh.closed_pnl() == pytest.approx(total_pnl)

    def test_binance_find_order_distinguishes_unknown_from_outage(self):
        from ai_crypto_trader_tpu.shell.exchange import BinanceExchange

        class UnknownOrder(Exception):
            code = -2013

        class Sdk:
            mode = "unknown"

            def get_order(self, **kw):
                if self.mode == "unknown":
                    raise UnknownOrder("Order does not exist.")
                if self.mode == "outage":
                    raise ConnectionError("timed out")
                return {"orderId": 7, "status": "FILLED", "side": "BUY",
                        "origQty": "2.0", "executedQty": "2.0",
                        "price": "0.00000000",
                        "cummulativeQuoteQty": "200.0"}

        sdk = Sdk()
        ex = BinanceExchange(client=sdk)
        assert ex.find_order_by_client_id(SYMBOL, "x") is None  # truly unknown
        sdk.mode = "outage"
        with pytest.raises(ConnectionError):          # must propagate
            ex.find_order_by_client_id(SYMBOL, "x")
        sdk.mode = "filled"
        found = ex.find_order_by_client_id(SYMBOL, "x")
        assert found["price"] == pytest.approx(100.0)  # quote/executed
