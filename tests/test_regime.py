"""Regime detection: clustering primitives, HMM correctness, and the
end-to-end detector against the synthetic generator's known regimes."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from ai_crypto_trader_tpu.regime import (
    RegimeDetector,
    gmm_fit,
    gmm_predict_proba,
    hmm_fit,
    hmm_posteriors,
    hmm_viterbi,
    kmeans_fit,
    kmeans_predict,
    pca_fit,
    regime_features,
    standardize_fit,
)

KEY = jax.random.PRNGKey(3)


def _blobs(n=300, k=3, sep=6.0, f=4, seed=0):
    rng = np.random.default_rng(seed)
    centers = rng.normal(0, sep, (k, f))
    labels = rng.integers(0, k, n)
    return (centers[labels] + rng.normal(0, 1.0, (n, f))).astype(np.float32), labels


class TestCluster:
    def test_standardize(self):
        x, _ = _blobs()
        z = standardize_fit(jnp.asarray(x)).transform(jnp.asarray(x))
        np.testing.assert_allclose(np.asarray(z).mean(axis=0), 0, atol=1e-4)
        np.testing.assert_allclose(np.asarray(z).std(axis=0), 1, atol=1e-4)

    def test_pca_orthonormal(self):
        x, _ = _blobs(f=6)
        p = pca_fit(jnp.asarray(x), 3)
        comps = np.asarray(p.components)
        np.testing.assert_allclose(comps.T @ comps, np.eye(3), atol=1e-4)

    def test_kmeans_separates_blobs(self):
        x, labels = _blobs()
        km = kmeans_fit(KEY, jnp.asarray(x), 3)
        pred = np.asarray(kmeans_predict(km, jnp.asarray(x)))
        # cluster purity: majority label per cluster should dominate
        purity = sum((np.bincount(labels[pred == c]).max() if (pred == c).any() else 0)
                     for c in range(3)) / len(labels)
        assert purity > 0.95

    def test_gmm_probs_sum_to_one(self):
        x, _ = _blobs()
        g = gmm_fit(KEY, jnp.asarray(x), 3)
        p = np.asarray(gmm_predict_proba(g, jnp.asarray(x)))
        np.testing.assert_allclose(p.sum(axis=1), 1.0, atol=1e-5)
        assert (p.max(axis=1) > 0.9).mean() > 0.8  # well-separated → confident


class TestHMM:
    def _chain(self, n=600, seed=0):
        """2-state chain with distinct Gaussian emissions."""
        rng = np.random.default_rng(seed)
        A = np.array([[0.95, 0.05], [0.05, 0.95]])
        states = np.zeros(n, dtype=int)
        for t in range(1, n):
            states[t] = rng.choice(2, p=A[states[t - 1]])
        means = np.array([[-2.0], [2.0]])
        x = means[states] + rng.normal(0, 0.7, (n, 1))
        return x.astype(np.float32), states

    def test_posteriors_recover_states(self):
        x, states = self._chain()
        hmm = hmm_fit(KEY, jnp.asarray(x), 2)
        gamma, ll = hmm_posteriors(hmm, jnp.asarray(x))
        pred = np.asarray(jnp.argmax(gamma, axis=1))
        acc = max((pred == states).mean(), (1 - pred == states).mean())
        assert acc > 0.9
        assert np.isfinite(float(ll))

    def test_viterbi_matches_posterior_mostly(self):
        x, _ = self._chain()
        hmm = hmm_fit(KEY, jnp.asarray(x), 2)
        gamma, _ = hmm_posteriors(hmm, jnp.asarray(x))
        vit = np.asarray(hmm_viterbi(hmm, jnp.asarray(x)))
        post = np.asarray(jnp.argmax(gamma, axis=1))
        assert (vit == post).mean() > 0.95

    def test_learned_transitions_sticky(self):
        x, _ = self._chain()
        hmm = hmm_fit(KEY, jnp.asarray(x), 2)
        A = np.exp(np.asarray(hmm.log_A))
        assert A[0, 0] > 0.8 and A[1, 1] > 0.8


class TestDetector:
    @pytest.mark.parametrize("method", ["kmeans", "gmm", "hmm", "rules"])
    @pytest.mark.slow
    def test_fit_detect(self, ohlcv, method):
        arrays = {k: jnp.asarray(v) for k, v in ohlcv.items() if k != "regime"}
        det = RegimeDetector(method=method).fit(arrays)
        out = det.detect(arrays)
        assert out["regime"] in ("bull", "bear", "ranging", "volatile")
        assert 0 < out["confidence"] <= 1.0
        np.testing.assert_allclose(sum(out["probabilities"].values()), 1.0,
                                   atol=1e-4)

    def test_features_shape(self, ohlcv):
        arrays = {k: jnp.asarray(v) for k, v in ohlcv.items() if k != "regime"}
        f = regime_features(arrays)
        assert f.shape == (len(ohlcv["close"]), 6)
        assert np.isfinite(np.asarray(f)).all()

    def test_label_series_tracks_volatile_regime(self, ohlcv):
        """The synthetic generator's high-vol regime (2) should mostly map to
        'volatile'/'bear' labels rather than calm ones."""
        arrays = {k: jnp.asarray(v) for k, v in ohlcv.items() if k != "regime"}
        det = RegimeDetector(method="kmeans").fit(arrays)
        labels = det.label_series(arrays)
        true = np.asarray(ohlcv["regime"])
        vol_mask = true == 2
        if vol_mask.sum() > 50:
            frac_volatile = (labels[vol_mask] == 3).mean()
            frac_volatile_elsewhere = (labels[~vol_mask] == 3).mean()
            assert frac_volatile >= frac_volatile_elsewhere
