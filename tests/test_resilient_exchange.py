"""ResilientExchange: breaker + rate-limit + retry wiring at the adapter
seam (reference wiring: `services/market_monitor_service.py:96-115`)."""

import pytest

from ai_crypto_trader_tpu.shell.exchange import (
    ExchangeInterface,
    ExchangeUnavailable,
    ResilientExchange,
    make_exchange,
)
from ai_crypto_trader_tpu.utils.circuit_breaker import CircuitState


class FlakyClient(ExchangeInterface):
    """Fails the first `fail_first` calls of each method, then succeeds."""

    def __init__(self, fail_first=0):
        self.fail_first = fail_first
        self.calls = {}

    def _maybe_fail(self, name):
        n = self.calls.get(name, 0)
        self.calls[name] = n + 1
        if n < self.fail_first:
            raise ConnectionError(f"{name} flake #{n}")

    def get_ticker(self, symbol):
        self._maybe_fail("get_ticker")
        return {"symbol": symbol, "price": 100.0}

    def get_order_book(self, symbol, limit=20):
        self._maybe_fail("get_order_book")
        return {"bids": [], "asks": []}

    def get_klines(self, symbol, interval="1m", limit=100):
        self._maybe_fail("get_klines")
        return []

    def place_order(self, symbol, side, order_type, quantity, price=None,
                    stop_price=None):
        self._maybe_fail("place_order")
        return {"order_id": 1, "status": "FILLED"}

    def cancel_order(self, symbol, order_id):
        self._maybe_fail("cancel_order")
        return {"status": "CANCELED"}

    def get_balances(self):
        self._maybe_fail("get_balances")
        return {"USDC": 1000.0}


class VirtualClock:
    def __init__(self):
        self.t = 0.0
        self.sleeps = []

    def now(self):
        return self.t

    def sleep(self, dt):
        self.sleeps.append(dt)
        self.t += dt


def make_resilient(client, clock, **kw):
    return ResilientExchange(client, now_fn=clock.now, sleep=clock.sleep,
                             **kw)


def test_reads_retry_through_transient_failures():
    clock, client = VirtualClock(), FlakyClient(fail_first=2)
    ex = make_resilient(client, clock, max_read_retries=2)
    assert ex.get_ticker("BTCUSDC")["price"] == 100.0
    assert client.calls["get_ticker"] == 3          # 2 flakes + success
    assert len(clock.sleeps) == 2                   # backoff between tries
    assert ex.breaker.failures == 0                 # recovered read ≠ failure


def test_exhausted_read_counts_one_breaker_failure_and_raises():
    clock, client = VirtualClock(), FlakyClient(fail_first=99)
    ex = make_resilient(client, clock, max_read_retries=1)
    with pytest.raises(ExchangeUnavailable):
        ex.get_ticker("BTCUSDC")
    assert ex.breaker.failures == 1


def test_breaker_trips_open_then_half_open_recovers():
    clock = VirtualClock()
    client = FlakyClient(fail_first=6)              # 3 reads × 2 attempts
    ex = make_resilient(client, clock, max_read_retries=1,
                        failure_threshold=3, reset_timeout_s=30.0)
    for _ in range(3):
        with pytest.raises(ExchangeUnavailable):
            ex.get_ticker("BTCUSDC")
    assert ex.breaker.state is CircuitState.OPEN
    inner_calls = client.calls["get_ticker"]

    # while open: rejected WITHOUT touching the inner client
    with pytest.raises(ExchangeUnavailable):
        ex.get_ticker("BTCUSDC")
    assert client.calls["get_ticker"] == inner_calls

    # after the reset timeout the half-open trial succeeds and closes it
    clock.t += 31.0
    assert ex.get_ticker("BTCUSDC")["price"] == 100.0
    assert ex.breaker.state is CircuitState.CLOSED


def test_order_placement_is_never_retried():
    clock, client = VirtualClock(), FlakyClient(fail_first=1)
    ex = make_resilient(client, clock)
    with pytest.raises(ExchangeUnavailable):
        ex.place_order("BTCUSDC", "BUY", "MARKET", 1.0)
    assert client.calls["place_order"] == 1         # exactly one attempt


def test_rate_limiter_sleeps_out_the_deficit():
    clock, client = VirtualClock(), FlakyClient()
    ex = make_resilient(client, clock, rate_per_s=1.0, burst=2.0)
    ex.get_ticker("A")
    ex.get_ticker("B")                              # burst exhausted
    ex.get_ticker("C")                              # must wait ~1s
    assert any(s >= 0.99 for s in clock.sleeps)


def test_resilient_fake_delegates_paper_trading_surface():
    from ai_crypto_trader_tpu.data.synthetic import generate_ohlcv
    from ai_crypto_trader_tpu.data.ingest import from_dict

    series = from_dict(generate_ohlcv(n=32, seed=5))
    ex = make_exchange("fake", resilient=True, series={"BTCUSDC": series})
    assert isinstance(ex, ResilientExchange)
    ex.advance("BTCUSDC")                           # delegated virtual clock
    assert ex.get_ticker("BTCUSDC")["price"] > 0
    assert ex.fills == []                           # delegated attribute


def test_open_circuit_rejects_before_burning_tokens():
    clock = VirtualClock()
    client = FlakyClient(fail_first=99)
    ex = make_resilient(client, clock, max_read_retries=0,
                        failure_threshold=1, rate_per_s=1.0, burst=1.0)
    with pytest.raises(ExchangeUnavailable):
        ex.get_ticker("A")                          # trips the breaker
    tokens_before = ex.bucket.tokens
    sleeps_before = len(clock.sleeps)
    with pytest.raises(ExchangeUnavailable):
        ex.get_ticker("B")                          # rejected at the door
    assert ex.bucket.tokens == tokens_before
    assert len(clock.sleeps) == sleeps_before


@pytest.mark.slow
def test_trading_system_survives_exchange_outage_and_recovers():
    """Full-pipeline drive: an outage mid-run must skip ticks (alert, no
    crash) and the system must resume after the breaker's reset window."""
    import asyncio

    from ai_crypto_trader_tpu.data.ingest import from_dict
    from ai_crypto_trader_tpu.data.synthetic import generate_ohlcv
    from ai_crypto_trader_tpu.shell.exchange import FakeExchange
    from ai_crypto_trader_tpu.shell.launcher import TradingSystem

    series = from_dict(generate_ohlcv(n=700, seed=5), symbol="BTCUSDC")
    inner = FakeExchange({"BTCUSDC": series})
    inner.advance("BTCUSDC", steps=600)

    clock = VirtualClock()
    outage = {"on": False}

    class Outage(FakeExchange):
        pass

    real_klines = inner.get_klines

    def flaky_klines(*a, **kw):
        if outage["on"]:
            raise ConnectionError("exchange down")
        return real_klines(*a, **kw)

    inner.get_klines = flaky_klines
    ex = ResilientExchange(inner, now_fn=clock.now, sleep=clock.sleep,
                           max_read_retries=0, failure_threshold=1,
                           reset_timeout_s=30.0)
    system = TradingSystem(ex, ["BTCUSDC"], now_fn=clock.now)

    async def go():
        r = await system.tick()
        assert "skipped" not in r

        outage["on"] = True
        inner.advance("BTCUSDC")
        clock.t += 60.0
        r = await system.tick()
        assert "skipped" in r                      # cycle skipped, no crash
        assert any("errors_total" in k and "exchange_unavailable" in k
                   for k in system.metrics.counters)

        outage["on"] = False
        inner.advance("BTCUSDC")
        clock.t += 60.0                            # > reset_timeout_s
        r = await system.tick()
        assert "skipped" not in r                  # recovered

    asyncio.run(go())


def test_filled_buy_with_dead_protection_stays_managed():
    """Outage between the market-BUY fill and the protective-order
    placement must leave the position on the books (unprotected), and the
    next price update must repair the missing SL/TP orders."""
    import asyncio

    from ai_crypto_trader_tpu.data.ingest import from_dict
    from ai_crypto_trader_tpu.data.synthetic import generate_ohlcv
    from ai_crypto_trader_tpu.shell.bus import EventBus
    from ai_crypto_trader_tpu.shell.exchange import FakeExchange
    from ai_crypto_trader_tpu.shell.executor import TradeExecutor
    from ai_crypto_trader_tpu.config import TradingParams

    series = from_dict(generate_ohlcv(n=64, seed=5), symbol="BTCUSDC")
    inner = FakeExchange({"BTCUSDC": series}, quote_balance=10_000.0)
    inner.advance("BTCUSDC", steps=30)

    outage = {"on": False}
    real_place = inner.place_order

    def place(symbol, side, order_type, quantity, price=None, stop_price=None,
              **kw):
        if outage["on"] and order_type != "MARKET":
            raise ConnectionError("down")
        return real_place(symbol, side, order_type, quantity, price,
                          stop_price, **kw)

    inner.place_order = place
    clock = VirtualClock()
    ex = ResilientExchange(inner, now_fn=clock.now, sleep=clock.sleep,
                           max_read_retries=0, failure_threshold=100)
    execu = TradeExecutor(EventBus(now_fn=clock.now), ex,
                          trading=TradingParams(ai_confidence_threshold=0.0,
                                                min_signal_strength=0.0,
                                                min_trade_amount=1.0),
                          now_fn=clock.now)
    price = inner.get_ticker("BTCUSDC")["price"]
    signal = {"symbol": "BTCUSDC", "signal": "BUY", "decision": "BUY",
              "confidence": 1.0, "signal_strength": 100.0,
              "current_price": price, "volatility": 0.015,
              "avg_volume": 60_000.0}

    async def go():
        outage["on"] = True                 # protective legs will fail
        trade = await execu.handle_signal(signal)
        assert trade is not None            # position registered anyway
        assert trade.stop_order_id is None and trade.tp_order_id is None
        assert "BTCUSDC" in execu.active_trades

        outage["on"] = False                # exchange back: repair on tick
        await execu.on_price("BTCUSDC", price)
        t = execu.active_trades["BTCUSDC"]
        assert t.stop_order_id is not None and t.tp_order_id is not None
        assert inner.order_is_open("BTCUSDC", t.stop_order_id)

    asyncio.run(go())


def test_factory_wraps_binance_by_default():
    class SdkStub:                                  # binance.Client surface
        def get_symbol_ticker(self, symbol):
            return {"price": "100.0"}

    ex = make_exchange("binance", client=SdkStub())
    assert isinstance(ex, ResilientExchange)
    assert ex.get_ticker("BTCUSDC")["price"] == 100.0
