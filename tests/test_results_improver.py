"""Result analyzer + systematic improver."""

import pytest
import asyncio
import json
import os

import numpy as np
import jax.numpy as jnp

from ai_crypto_trader_tpu.backtest.results import (
    comparison_table,
    load_results,
    render_report_html,
    summary_report,
)
from ai_crypto_trader_tpu.config import EvolutionParams, GAParams
from ai_crypto_trader_tpu.data.synthetic import generate_ohlcv
from ai_crypto_trader_tpu.shell.bus import EventBus
from ai_crypto_trader_tpu.strategy.evolution import StrategyEvolver
from ai_crypto_trader_tpu.strategy.improver import SystematicImprover


def _write_results(d, n=3):
    os.makedirs(d, exist_ok=True)
    for i in range(n):
        with open(os.path.join(d, f"r{i}.json"), "w") as f:
            json.dump({"symbol": "BTCUSDC" if i < 2 else "ETHUSDC",
                       "strategy": "s", "sharpe_ratio": float(i),
                       "win_rate": 50.0 + i, "total_return_pct": i * 2.0,
                       "max_drawdown_pct": 5.0, "total_trades": 10 + i,
                       "initial_balance": 10_000.0,
                       "final_balance": 10_000.0 + 100 * i}, f)


class TestResults:
    def test_load_filter_summarize(self, tmp_path):
        d = str(tmp_path / "res")
        _write_results(d)
        all_ = load_results(d)
        assert len(all_) == 3
        btc = load_results(d, symbol="BTCUSDC")
        assert len(btc) == 2
        s = summary_report(all_)
        assert s["n_runs"] == 3 and s["best_sharpe"] == 2.0
        assert s["best_run"] == "r2.json"
        assert s["profitable_runs"] == 2   # r0 is flat

    def test_comparison_and_report(self, tmp_path):
        d = str(tmp_path / "res")
        _write_results(d)
        results = load_results(d)
        cmp_ = comparison_table(results)
        assert cmp_["ranked"][0] == "r2.json"
        path = render_report_html(results, str(tmp_path / "report.html"),
                                  equity_curve=np.linspace(1e4, 1.1e4, 40),
                                  drawdown_curve=np.linspace(0, 3, 40))
        html = open(path).read()
        assert html.count("<svg") == 2 and "Summary" in html

    def test_corrupt_file_skipped(self, tmp_path):
        d = str(tmp_path / "res")
        _write_results(d, 1)
        with open(os.path.join(d, "bad.json"), "w") as f:
            f.write("{not json")
        assert len(load_results(d)) == 1


class TestImprover:
    @pytest.mark.slow
    def test_improve_iterates_and_reports(self):
        async def go():
            d = generate_ohlcv(n=600, seed=4)
            arrays = {k: jnp.asarray(v) for k, v in d.items() if k != "regime"}
            ev = StrategyEvolver(EventBus(), cfg=EvolutionParams(
                ga=GAParams(population_size=4, generations=1)))
            imp = SystematicImprover(ev, cv_folds=2, max_iterations=2,
                                     target_sharpe=999.0)  # force iterations
            out = await imp.improve(arrays, regime="bull")
            assert out["iterations"] >= 1
            assert not out["converged"]
            rep = imp.report()
            assert rep["iterations"] == out["iterations"]
            assert "ga" in rep["methods_used"]
            # best-by-CV is monotone vs seed
            assert out["evaluation"]["mean_sharpe"] >= imp.history[0]["eval"]["mean_sharpe"] - 1e-9
        asyncio.run(go())

    def test_early_stop_when_target_met(self):
        async def go():
            d = generate_ohlcv(n=400, seed=4)
            arrays = {k: jnp.asarray(v) for k, v in d.items() if k != "regime"}
            ev = StrategyEvolver(EventBus())
            imp = SystematicImprover(ev, cv_folds=2, target_sharpe=-999.0)
            out = await imp.improve(arrays)
            assert out["converged"] and out["iterations"] == 0
        asyncio.run(go())
