"""Ring attention vs a dense single-device oracle.

The sequence axis sharded 8 ways must reproduce full softmax attention
exactly (f32 tolerance): the ring's online-softmax accumulation over
rotating K/V blocks is algebraically the same softmax, so every element —
including ones whose query and keys live on different devices — has to
match the materialized [T, T] computation (parallel/ring_attention.py;
reference has no long-context path at all, SURVEY §5.7)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ai_crypto_trader_tpu.parallel.ring_attention import (
    reference_attention,
    ring_self_attention,
)
from ai_crypto_trader_tpu.parallel.mesh import make_mesh

# Slow tier (VERDICT r4 next#3): golden-parity / end-to-end /
# training / sharded-compile suite — deselected by the default
# run, executed via `pytest -m slow`.
pytestmark = pytest.mark.slow


T, H, D = 256, 4, 16


@pytest.fixture(scope="module")
def qkv():
    rng = np.random.default_rng(3)
    mk = lambda: jnp.asarray(rng.normal(0, 1, (T, H, D)), jnp.float32)
    return mk(), mk(), mk()


class TestRingMatchesDense:
    @pytest.mark.parametrize("causal", [True, False])
    def test_parity(self, mesh8, qkv, causal):
        q, k, v = qkv
        want = np.asarray(reference_attention(q, k, v, causal=causal))
        got = np.asarray(
            ring_self_attention(q, k, v, mesh8, causal=causal))
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)

    def test_cross_device_rows_match(self, mesh8, qkv):
        """Rows whose causal window spans several devices' K/V blocks are
        where a broken rotation would show."""
        q, k, v = qkv
        want = np.asarray(reference_attention(q, k, v, causal=True))
        got = np.asarray(ring_self_attention(q, k, v, mesh8, causal=True))
        blk = T // 8
        for row in (blk, 3 * blk + 1, T - 1):
            np.testing.assert_allclose(got[row], want[row],
                                       rtol=2e-5, atol=2e-5)

    def test_output_is_sequence_sharded(self, mesh8, qkv):
        q, k, v = qkv
        out = ring_self_attention(q, k, v, mesh8)
        assert len(out.sharding.device_set) == 8

    def test_single_device_degenerates(self, qkv):
        q, k, v = qkv
        mesh1 = make_mesh(data_parallel=1, model_parallel=1,
                          devices=jax.devices()[:1])
        got = np.asarray(ring_self_attention(q, k, v, mesh1, causal=True))
        want = np.asarray(reference_attention(q, k, v, causal=True))
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)

    def test_indivisible_length_raises(self, mesh8):
        q = jnp.zeros((250, H, D), jnp.float32)
        with pytest.raises(ValueError, match="divisible"):
            ring_self_attention(q, q, q, mesh8)


class TestCausality:
    def test_future_keys_have_no_influence(self, mesh8, qkv):
        """Perturbing the last K/V block must leave every earlier causal
        output untouched — across device boundaries."""
        q, k, v = qkv
        base = np.asarray(ring_self_attention(q, k, v, mesh8, causal=True))
        blk = T // 8
        v2 = v.at[-blk:].add(100.0)
        k2 = k.at[-blk:].add(1.0)
        pert = np.asarray(ring_self_attention(q, k2, v2, mesh8, causal=True))
        np.testing.assert_allclose(pert[: T - blk], base[: T - blk],
                                   rtol=2e-5, atol=2e-5)
        assert not np.allclose(pert[T - blk:], base[T - blk:])

    def test_first_row_attends_only_itself(self, mesh8, qkv):
        q, k, v = qkv
        got = np.asarray(ring_self_attention(q, k, v, mesh8, causal=True))
        np.testing.assert_allclose(got[0], np.asarray(v[0], np.float32),
                                   rtol=1e-5, atol=1e-5)
