"""Risk stack: VaR/CVaR vs numpy oracles, trailing-stop state machine
invariants, adaptive stops, social adjustment caps and gates."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from ai_crypto_trader_tpu.config import SocialRiskParams
from ai_crypto_trader_tpu.risk import (
    SocialSnapshot,
    adaptive_stop_loss,
    correlation_matrix,
    cvar,
    diversification_analysis,
    equal_risk_position_sizes,
    historical_var,
    parametric_var,
    portfolio_var,
    social_risk_adjustment,
    trailing_stop_init,
    trailing_stop_update,
    weighted_sentiment,
)


@pytest.fixture
def returns(rng):
    return jnp.asarray(rng.normal(0.0002, 0.02, (4, 500)).astype(np.float32))


class TestVaR:
    def test_historical_matches_numpy(self, returns):
        r = np.asarray(returns)
        ours = np.asarray(historical_var(returns, 0.95))
        ref = np.maximum(-np.quantile(r, 0.05, axis=-1), 0)
        np.testing.assert_allclose(ours, ref, rtol=1e-4)

    def test_cvar_geq_var(self, returns):
        v = np.asarray(historical_var(returns))
        c = np.asarray(cvar(returns))
        assert (c >= v - 1e-6).all()

    def test_parametric_scales_with_vol(self, rng):
        lo = jnp.asarray(rng.normal(0, 0.01, 1000).astype(np.float32))
        hi = jnp.asarray(rng.normal(0, 0.03, 1000).astype(np.float32))
        assert float(parametric_var(hi)) > float(parametric_var(lo)) * 2

    def test_correlation_matrix(self, returns):
        ours = np.asarray(correlation_matrix(returns))
        ref = np.corrcoef(np.asarray(returns))
        np.testing.assert_allclose(ours, ref, atol=1e-4)

    def test_portfolio_var_diversification(self, rng):
        """Two uncorrelated assets: portfolio VaR < weighted sum of VaRs."""
        a = rng.normal(0, 0.02, 2000)
        b = rng.normal(0, 0.02, 2000)
        rets = jnp.asarray(np.stack([a, b]).astype(np.float32))
        w = jnp.asarray([0.5, 0.5])
        pv = float(portfolio_var(w, rets))
        individual = np.asarray(parametric_var(rets))
        assert pv < individual.mean() * 0.9

    def test_equal_risk_sizes(self):
        vols = jnp.asarray([0.01, 0.02, 0.04])
        w = np.asarray(equal_risk_position_sizes(vols, max_allocation=1.0))
        np.testing.assert_allclose(w.sum(), 1.0, rtol=1e-4)
        assert w[0] > w[1] > w[2]       # lower vol → bigger size
        w_capped = np.asarray(equal_risk_position_sizes(vols, max_allocation=0.4))
        assert w_capped.max() <= 0.4 + 1e-5

    def test_diversification_analysis(self, returns):
        w = jnp.asarray([0.25] * 4)
        out = {k: float(v) for k, v in diversification_analysis(w, returns).items()}
        assert 3.5 < out["effective_assets"] <= 4.01
        assert out["diversification_ratio"] >= 1.0


class TestAdaptiveStop:
    def test_vol_widens_stop(self):
        _, pct_lo = adaptive_stop_loss(100.0, 0.05, base_stop_pct=2.0)
        _, pct_hi = adaptive_stop_loss(100.0, 0.50, base_stop_pct=2.0)
        # vol 0.05 → vol_pct 0.1 → factor 0.5 + 1.5·0.1 = 0.65 → 1.3 %
        np.testing.assert_allclose(float(pct_lo), 1.3, rtol=1e-5)
        np.testing.assert_allclose(float(pct_hi), 4.0, rtol=1e-5)  # max factor 2

    def test_price_formula(self):
        price, pct = adaptive_stop_loss(200.0, 0.25)
        np.testing.assert_allclose(float(price), 200 * (1 - float(pct) / 100), rtol=1e-6)


class TestTrailingStop:
    def test_activation_then_ratchet(self):
        st = trailing_stop_init(100.0, 98.0, activation_threshold_pct=1.0)
        st, trig = trailing_stop_update(st, 100.5)      # below activation
        assert not bool(st.activated) and not bool(trig)
        st, trig = trailing_stop_update(st, 101.5)      # activates
        assert bool(st.activated)
        st, trig = trailing_stop_update(st, 103.0)      # new high → adjust
        stop_after_high = float(st.stop)
        assert stop_after_high > 98.0
        np.testing.assert_allclose(stop_after_high, 103.0 * (1 - 0.8 / 100), rtol=1e-5)

    def test_stop_never_moves_down(self):
        st = trailing_stop_init(100.0, 98.0)
        prices = [102.0, 105.0, 103.0, 101.0, 104.0]
        stops = []
        for p in prices:
            st, _ = trailing_stop_update(st, p)
            stops.append(float(st.stop))
        assert all(b >= a - 1e-6 for a, b in zip(stops, stops[1:]))

    def test_trigger_fires(self):
        st = trailing_stop_init(100.0, 98.0)
        st, _ = trailing_stop_update(st, 105.0)          # activate + ratchet
        st, trig = trailing_stop_update(st, float(st.stop) - 0.01)
        assert bool(trig)

    @pytest.mark.parametrize("strategy,kw", [
        ("atr_based", {"atr": 1.5}),
        ("volatility_based", {"volatility": 2.0}),
        ("fixed_amount", {"fixed_trail_amount": 3.0}),
    ])
    def test_other_strategies(self, strategy, kw):
        st = trailing_stop_init(100.0, 95.0)
        st, _ = trailing_stop_update(st, 110.0, strategy=strategy, **kw)
        assert float(st.stop) > 95.0
        if strategy == "atr_based":
            np.testing.assert_allclose(float(st.stop), 110 - 1.5 * 2.0, rtol=1e-5)


class TestSocial:
    def _snap(self, s, age=0.0, q=1.0):
        return SocialSnapshot(
            sentiments=jnp.full((1, 4), jnp.asarray(s, jnp.float32)),
            age_hours=jnp.asarray([age], jnp.float32),
            data_quality=jnp.asarray(q, jnp.float32))

    def test_half_life_decay(self):
        old = SocialSnapshot(
            sentiments=jnp.asarray([[1.0] * 4, [0.0] * 4], jnp.float32),
            age_hours=jnp.asarray([0.0, 6.0], jnp.float32),
            data_quality=jnp.asarray(1.0))
        # weight of 6h-old obs is exactly half → (1·1 + 0·0.5)/1.5 = 2/3
        np.testing.assert_allclose(float(weighted_sentiment(old)), 2 / 3, rtol=1e-4)

    def test_bullish_sizes_up_bearish_down(self):
        up = social_risk_adjustment(self._snap(0.9))
        dn = social_risk_adjustment(self._snap(0.1))
        assert float(up["position_size_factor"]) > 1.0
        assert float(dn["position_size_factor"]) < 1.0

    def test_neutral_band_is_exact_one(self):
        mid = social_risk_adjustment(self._snap(0.5))
        np.testing.assert_allclose(float(mid["position_size_factor"]), 1.0)

    def test_caps_respected(self):
        p = SocialRiskParams(max_adjustment_percent=0.5)
        out = social_risk_adjustment(self._snap(1.0), p)
        for k in ("position_size_factor", "stop_loss_factor",
                  "take_profit_factor", "correlation_limit_factor"):
            assert 0.5 - 1e-6 <= float(out[k]) <= 1.5 + 1e-6

    def test_quality_gate_neutralizes(self):
        out = social_risk_adjustment(self._snap(1.0, q=0.2))
        np.testing.assert_allclose(float(out["position_size_factor"]), 1.0)
        assert not bool(out["data_quality_ok"])
