"""RL: env semantics (long-only position accounting, episode structure) and
DQN training machinery (replay ring, target sync, ε decay, learning)."""

import pytest
import numpy as np
import jax
import jax.numpy as jnp

from ai_crypto_trader_tpu import ops
from ai_crypto_trader_tpu.rl import (
    DQNConfig,
    dqn_init,
    env_reset,
    env_step,
    evaluate_policy,
    make_env_params,
    train_dqn,
    train_iteration,
)
from ai_crypto_trader_tpu.rl.env import BUY, HOLD, SELL

# Slow tier (VERDICT r4 next#3): golden-parity / end-to-end /
# training / sharded-compile suite — deselected by the default
# run, executed via `pytest -m slow`.
pytestmark = pytest.mark.slow


KEY = jax.random.PRNGKey(0)


def _env_params(ohlcv, n=512, episode_len=64, fee=0.0):
    arrays = {k: jnp.asarray(v[:n]) for k, v in ohlcv.items() if k != "regime"}
    ind = ops.compute_indicators(arrays)
    return make_env_params(ind, episode_len=episode_len, fee_rate=fee)


class TestEnv:
    def test_reset_obs_shape(self, ohlcv):
        p = _env_params(ohlcv)
        s, obs = env_reset(p, KEY)
        assert obs.shape == (10,)
        assert not bool(s.in_pos)
        np.testing.assert_allclose(float(s.balance), 1.0)

    def test_buy_hold_sell_accounting(self, ohlcv):
        p = _env_params(ohlcv)
        s, _ = env_reset(p, KEY)
        t0 = int(s.t)
        s, _, r1, _ = env_step(p, s, jnp.asarray(BUY))
        assert bool(s.in_pos)
        price_ret = (float(p.close[t0 + 1]) - float(p.close[t0])) / float(p.close[t0])
        np.testing.assert_allclose(float(r1), price_ret, rtol=1e-5)
        s, _, r2, _ = env_step(p, s, jnp.asarray(SELL))
        assert not bool(s.in_pos)
        np.testing.assert_allclose(float(r2), 0.0, atol=1e-7)  # exited at t+1 price

    def test_hold_when_flat_gives_zero(self, ohlcv):
        p = _env_params(ohlcv)
        s, _ = env_reset(p, KEY)
        for _ in range(3):
            s, _, r, _ = env_step(p, s, jnp.asarray(HOLD))
            np.testing.assert_allclose(float(r), 0.0, atol=1e-7)
        np.testing.assert_allclose(float(s.balance), 1.0, rtol=1e-6)

    def test_fees_charged(self, ohlcv):
        p = _env_params(ohlcv, fee=0.001)
        s, _ = env_reset(p, KEY)
        _, _, r_fee, _ = env_step(p, s, jnp.asarray(BUY))
        p0 = _env_params(ohlcv, fee=0.0)
        s0, _ = env_reset(p0, KEY)
        _, _, r_nofee, _ = env_step(p0, s0, jnp.asarray(BUY))
        np.testing.assert_allclose(float(r_nofee) - float(r_fee), 0.001, rtol=1e-4)

    def test_done_at_episode_end(self, ohlcv):
        p = _env_params(ohlcv, episode_len=5)
        s, _ = env_reset(p, KEY)
        done = False
        for i in range(5):
            s, _, _, done = env_step(p, s, jnp.asarray(HOLD))
        assert bool(done)

    def test_episode_longer_than_series_terminates(self, ohlcv):
        p = _env_params(ohlcv, n=40, episode_len=500)
        s, _ = env_reset(p, KEY)
        done = False
        for _ in range(45):
            s, _, _, done = env_step(p, s, jnp.asarray(HOLD))
            if bool(done):
                break
        assert bool(done), "episode must terminate at end of data"
        assert int(s.t) <= 40

    def test_vmapped_envs_independent(self, ohlcv):
        p = _env_params(ohlcv)
        keys = jax.random.split(KEY, 8)
        states, obs = jax.vmap(lambda k: env_reset(p, k))(keys)
        assert obs.shape == (8, 10)
        assert len(np.unique(np.asarray(states.t))) > 1  # different offsets


class TestDQN:
    CFG = DQNConfig(num_envs=8, replay_capacity=512, batch_size=16,
                    rollout_len=4, learn_steps_per_iter=2,
                    target_sync_every=3)

    def test_init_shapes(self, ohlcv):
        p = _env_params(ohlcv)
        st = dqn_init(KEY, p, self.CFG)
        assert st.obs.shape == (8, 10)
        assert int(st.replay.size) == 0

    def test_iteration_fills_replay_and_learns(self, ohlcv):
        p = _env_params(ohlcv)
        st = dqn_init(KEY, p, self.CFG)
        st2, m = train_iteration(p, st, self.CFG)
        assert int(st2.replay.size) == 32  # 4 steps × 8 envs
        assert int(st2.learn_steps) == 2
        assert float(st2.epsilon) < float(st.epsilon)
        assert np.isfinite(float(m["loss"]))
        # params actually updated
        leaf0 = jax.tree.leaves(st.params)[0]
        leaf2 = jax.tree.leaves(st2.params)[0]
        assert not np.allclose(np.asarray(leaf0), np.asarray(leaf2))

    def test_target_sync_happens(self, ohlcv):
        p = _env_params(ohlcv)
        st = dqn_init(KEY, p, self.CFG)
        # after 2 iterations learn_steps=4 ≥ sync interval 3 → target != init
        for _ in range(2):
            st, _ = train_iteration(p, st, self.CFG)
        t0 = jax.tree.leaves(st.target_params)[0]
        pr = jax.tree.leaves(st.params)[0]
        init = jax.tree.leaves(dqn_init(KEY, p, self.CFG).target_params)[0]
        assert not np.allclose(np.asarray(t0), np.asarray(init))

    def test_train_and_evaluate(self, ohlcv):
        p = _env_params(ohlcv)
        st, hist = train_dqn(KEY, p, self.CFG, iterations=3)
        assert np.isfinite(hist[-1]["loss"])
        out = evaluate_policy(p, st.params, self.CFG, KEY, n_steps=32)
        assert np.isfinite(float(out["mean_balance"]))
