"""Market scanner: discovery + one-pass vectorized ranking over a 50+ pair
fake universe (CryptoScanner.scan_market parity,
`binance_ml_strategy.py:293-468` — the reference walks pairs in a
ThreadPoolExecutor; here the whole universe is one [P, T] device pass)."""

import numpy as np
import pytest

from ai_crypto_trader_tpu.data.ingest import from_dict
from ai_crypto_trader_tpu.data.synthetic import generate_ohlcv
from ai_crypto_trader_tpu.shell.exchange import FakeExchange
from ai_crypto_trader_tpu.shell.scanner import MarketScanner

N_PAIRS = 56
LOOKBACK = 192


def _universe(n_pairs=N_PAIRS, n_hist=LOOKBACK + 8):
    series = {}
    for i in range(n_pairs):
        sym = f"A{i:03d}USDC"
        d = generate_ohlcv(n=n_hist, seed=500 + i, s0=100.0 * (1 + i),
                           base_vol=0.0004 * (1 + (i % 9)),
                           base_volume=40.0 * (1 + (i % 13)))
        series[sym] = from_dict(
            {k: v for k, v in d.items() if k != "regime"}, symbol=sym)
    # one illiquid dust pair that must be filtered out
    d = generate_ohlcv(n=n_hist, seed=999, s0=0.001, base_volume=0.0001)
    series["DUSTUSDC"] = from_dict(
        {k: v for k, v in d.items() if k != "regime"}, symbol="DUSTUSDC")
    # one pair on a different quote asset — excluded by discovery
    d = generate_ohlcv(n=n_hist, seed=998)
    series["ETHBTC"] = from_dict(
        {k: v for k, v in d.items() if k != "regime"}, symbol="ETHBTC")
    ex = FakeExchange(series)
    ex.advance(steps=n_hist)
    return ex


@pytest.fixture(scope="module")
def exchange():
    return _universe()


class TestDiscovery:
    def test_quote_filter(self, exchange):
        sc = MarketScanner(exchange, quote="USDC", lookback=LOOKBACK)
        syms = sc.discover()
        assert len(syms) == N_PAIRS + 1          # dust included, ETHBTC not
        assert "ETHBTC" not in syms
        assert all(s.endswith("USDC") for s in syms)

    def test_list_symbols_unfiltered(self, exchange):
        assert "ETHBTC" in exchange.list_symbols()


class TestRanking:
    def test_scan_ranks_and_filters(self, exchange):
        sc = MarketScanner(exchange, lookback=LOOKBACK, top_k=10)
        ranked = sc.scan()
        assert 0 < len(ranked) <= 10
        scores = [o["score"] for o in ranked]
        assert scores == sorted(scores, reverse=True)
        assert all(o["symbol"] != "DUSTUSDC" for o in ranked)
        assert all(o["quote_volume"] >= sc.min_quote_volume for o in ranked)
        assert all(sc.min_volatility <= o["volatility"] <= sc.max_volatility
                   for o in ranked)

    def test_top_symbols_feed_launcher(self, exchange):
        from ai_crypto_trader_tpu.shell.launcher import TradingSystem

        system = TradingSystem.with_discovery(
            exchange, scanner=MarketScanner(exchange, lookback=LOOKBACK,
                                            top_k=3))
        assert 0 < len(system.symbols) <= 3
        assert all(s.endswith("USDC") for s in system.symbols)
        assert system.scanner.last_scan  # discovery result retained

    def test_explicit_symbol_subset(self, exchange):
        sc = MarketScanner(exchange, lookback=LOOKBACK, top_k=50)
        subset = ["A000USDC", "A001USDC", "A002USDC"]
        ranked = sc.scan(subset)
        assert set(o["symbol"] for o in ranked) <= set(subset)

    def test_empty_universe(self):
        ex = FakeExchange({})
        sc = MarketScanner(ex)
        assert sc.scan() == []
        assert sc.top_symbols() == []


class TestScoreSemantics:
    @pytest.mark.slow
    def test_score_pairs_vectorized_matches_scalar(self, exchange):
        """Scoring P pairs in one pass == scoring each pair alone."""
        import jax.numpy as jnp

        from ai_crypto_trader_tpu.shell.scanner import score_pairs

        syms = ["A003USDC", "A007USDC", "A011USDC"]
        cols = {k: [] for k in ("open", "high", "low", "close", "volume")}
        for s in syms:
            rows = np.asarray(exchange.get_klines(s, limit=LOOKBACK),
                              np.float64)[:, 1:6].astype(np.float32)
            for j, k in enumerate(cols):
                cols[k].append(rows[:, j])
        batch = {k: jnp.asarray(np.stack(v)) for k, v in cols.items()}
        joint = score_pairs(batch)
        for i in range(len(syms)):
            solo = score_pairs({k: v[i] for k, v in batch.items()})
            np.testing.assert_allclose(float(joint["score"][i]),
                                       float(solo["score"]), rtol=1e-5)
