"""Service wrappers: social monitor service and market regime service."""

import asyncio

import numpy as np
import pytest

from ai_crypto_trader_tpu.regime.service import MarketRegimeService
from ai_crypto_trader_tpu.shell.bus import EventBus
from ai_crypto_trader_tpu.social.service import SocialMonitorService


def _bus_with_market(symbol="BTCUSDC", chg=2.0):
    bus = EventBus()
    bus.set(f"market_data_{symbol}", {
        "current_price": 100.0, "price_change_15m": chg, "rsi": 50.0,
        "volatility": 0.01, "trend_strength": 2.0, "signal_strength": 60.0,
        "timestamp": 0.0})
    return bus


class TestSocialService:
    def test_poll_publishes_and_caches(self):
        async def go():
            clock = {"t": 0.0}
            bus = _bus_with_market()
            svc = SocialMonitorService(bus, now_fn=lambda: clock["t"])
            n = await svc.poll()
            assert n == 1
            assert bus.get("social_metrics_BTCUSDC")["overall_sentiment"] > 0.5
            snap = bus.get("social_snapshot_BTCUSDC")
            assert snap.sentiments.shape[1] == 4
            # cached within ttl
            assert await svc.poll() == 0
            clock["t"] += 301.0
            assert await svc.poll() == 1
        asyncio.run(go())

    def test_accuracy_assessment_reweights(self):
        async def go():
            clock = {"t": 0.0}
            bus = _bus_with_market()
            svc = SocialMonitorService(bus, cache_ttl_s=0.0,
                                       now_fn=lambda: clock["t"])
            rng = np.random.default_rng(0)
            for i in range(80):
                chg = float(rng.normal(0, 2))
                bus.set("market_data_BTCUSDC",
                        {"current_price": 100.0, "price_change_15m": chg,
                         "timestamp": clock["t"]})
                await svc.poll(force=True)
                clock["t"] += 60.0
            close = 100 * np.cumprod(1 + rng.normal(0, 0.01, 80)).astype(np.float32)
            out = svc.assess_accuracy("BTCUSDC", close)
            assert set(out["accuracy"]) == {"twitter_sentiment",
                                            "reddit_sentiment",
                                            "news_sentiment",
                                            "overall_sentiment"}
            np.testing.assert_allclose(sum(out["weights"].values()), 1.0,
                                       rtol=1e-6)
        asyncio.run(go())


class TestRegimeService:
    def _bus_with_history(self, n=400, seed=3):
        from ai_crypto_trader_tpu.data.synthetic import generate_ohlcv
        bus = EventBus()
        d = generate_ohlcv(n=n, seed=seed)
        klines = [[i * 60000, float(d["open"][i]), float(d["high"][i]),
                   float(d["low"][i]), float(d["close"][i]),
                   float(d["volume"][i])] for i in range(n)]
        bus.set("historical_data_BTCUSDC_1m", klines)
        return bus

    def test_update_detects_and_publishes(self):
        async def go():
            bus = self._bus_with_history()
            svc = MarketRegimeService(bus, now_fn=lambda: 0.0)
            q = bus.subscribe("regime_updates")
            out = await svc.update("BTCUSDC")
            assert out["regime"] in ("bull", "bear", "ranging", "volatile")
            assert bus.get("market_regime")["regime"] == out["regime"]
            assert q.get_nowait()["data"]["regime"] == out["regime"]
        asyncio.run(go())

    def test_insufficient_history_keeps_default(self):
        async def go():
            bus = EventBus()
            svc = MarketRegimeService(bus)
            out = await svc.update("BTCUSDC")
            assert out["regime"] == "ranging" and out["confidence"] == 0.0
        asyncio.run(go())

    def test_per_regime_performance_and_switch(self):
        svc = MarketRegimeService(EventBus())
        svc.regimes["BTCUSDC"] = {"regime": "bull", "confidence": 0.9,
                                  "timestamp": 1.0}
        for _ in range(10):
            svc.record_trade("trend", 20.0)
            svc.record_trade("grid", -10.0)
        assert svc.regime_score("trend") > svc.regime_score("grid")
        assert svc.best_strategy_for_regime() == "trend"
        rec = svc.switch_recommendation("grid")
        assert rec["switch"] and rec["candidate"] == "trend"
        assert not svc.switch_recommendation("trend")["switch"]
