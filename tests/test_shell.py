"""Host shell: event bus, fake exchange matching, circuit breaker, rate
limiter, metrics exposition, checkpointing, and the full monitor → analyzer
→ executor pipeline on deterministic data — the integration test the
reference never had (its tests require live Binance + OpenAI, SURVEY §4)."""

import asyncio

import numpy as np
import pytest

from ai_crypto_trader_tpu.config import TradingParams
from ai_crypto_trader_tpu.data.ingest import OHLCV
from ai_crypto_trader_tpu.data.synthetic import generate_ohlcv
from ai_crypto_trader_tpu.shell import (
    EventBus,
    FakeExchange,
    MarketMonitor,
    SignalAnalyzer,
    TradeExecutor,
)
from ai_crypto_trader_tpu.utils import (
    CircuitBreaker,
    MetricsRegistry,
    TokenBucket,
    load_checkpoint,
    retry_with_backoff,
    save_checkpoint,
)


def _series(n=600, seed=5, symbol="BTCUSDC"):
    d = generate_ohlcv(n=n, seed=seed)
    return OHLCV(timestamp=np.arange(n, dtype=np.int64) * 60_000,
                 open=d["open"], high=d["high"], low=d["low"],
                 close=d["close"], volume=d["volume"] * 1000, symbol=symbol)


class VirtualClock:
    def __init__(self):
        self.t = 1_000_000.0

    def __call__(self):
        return self.t


class TestBus:
    def test_pubsub_and_kv(self):
        async def go():
            bus = EventBus()
            q = bus.subscribe("market_updates")
            await bus.publish("market_updates", {"x": 1})
            env = q.get_nowait()
            assert env["data"] == {"x": 1}
            bus.set("holdings", {"BTC": 2})
            assert bus.get("holdings")["BTC"] == 2
            assert bus.keys("hold*") == ["holdings"]
        asyncio.run(go())

    def test_slow_consumer_drops_oldest(self):
        async def go():
            bus = EventBus(max_queue=2)
            q = bus.subscribe("c")
            for i in range(5):
                await bus.publish("c", i)
            assert q.get_nowait()["data"] == 3
            assert q.get_nowait()["data"] == 4
        asyncio.run(go())


class TestFakeExchange:
    def test_market_order_and_balances(self):
        ex = FakeExchange({"BTCUSDC": _series()}, quote_balance=10_000, fee_rate=0.0)
        px = ex.get_ticker("BTCUSDC")["price"]
        out = ex.place_order("BTCUSDC", "BUY", "MARKET", quantity=0.01)
        assert out["status"] == "FILLED" and out["price"] == px
        b = ex.get_balances()
        np.testing.assert_allclose(b["BTC"], 0.01)
        np.testing.assert_allclose(b["USDC"], 10_000 - 0.01 * px, rtol=1e-6)

    def test_insufficient_balance_rejected(self):
        ex = FakeExchange({"BTCUSDC": _series()}, quote_balance=10.0)
        out = ex.place_order("BTCUSDC", "BUY", "MARKET", quantity=100.0)
        assert out["status"] == "REJECTED"

    def test_stop_order_fills_on_breach(self):
        s = _series()
        ex = FakeExchange({"BTCUSDC": s}, quote_balance=1e9, fee_rate=0.0)
        ex.place_order("BTCUSDC", "BUY", "MARKET", quantity=1.0)
        px = ex.get_ticker("BTCUSDC")["price"]
        stop = px * 0.9995
        ex.place_order("BTCUSDC", "SELL", "STOP_LOSS", 1.0, stop_price=stop)
        for _ in range(400):
            ex.advance("BTCUSDC")
            if not ex.open_orders:
                break
        assert not ex.open_orders, "stop should eventually trigger"
        assert ex.fills[-1]["type"] == "STOP_LOSS"

    def test_order_book_shape(self):
        ex = FakeExchange({"BTCUSDC": _series()})
        ob = ex.get_order_book("BTCUSDC", limit=10)
        assert len(ob["bids"]) == 10 and len(ob["asks"]) == 10
        assert ob["bids"][0][0] < ob["asks"][0][0]


class TestResilience:
    def test_circuit_breaker_opens_and_recovers(self):
        clock = VirtualClock()
        br = CircuitBreaker("t", failure_threshold=2, reset_timeout_s=10,
                            now_fn=clock)
        boom = lambda: (_ for _ in ()).throw(RuntimeError("x"))
        assert br.call(lambda: 42) == 42
        br.call(boom); br.call(boom)
        assert br.state.value == "open"
        assert br.call(lambda: 42) is None            # rejected while open
        clock.t += 11
        assert br.call(lambda: 42) == 42              # half-open probe passes
        assert br.state.value == "closed"

    def test_retry_with_backoff(self):
        calls = []

        async def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise RuntimeError("nope")
            return "ok"

        async def fast_sleep(_):
            pass

        out = asyncio.run(retry_with_backoff(flaky, max_retries=5,
                                             sleep=fast_sleep))
        assert out == "ok" and len(calls) == 3

    def test_token_bucket(self):
        clock = VirtualClock()
        tb = TokenBucket(rate_per_s=1.0, capacity=2.0, now_fn=clock)
        assert tb.try_acquire() and tb.try_acquire()
        assert not tb.try_acquire()
        clock.t += 1.0
        assert tb.try_acquire()


class TestMetrics:
    def test_exposition(self):
        m = MetricsRegistry()
        m.inc("trades_executed_total", symbol="BTCUSDC")
        m.set_gauge("portfolio_value_usd", 12345.0)
        with m.measure_time("request_latency_seconds", service="x"):
            pass
        text = m.exposition()
        assert 'crypto_trader_tpu_trades_executed_total{symbol="BTCUSDC"} 1.0' in text
        assert "crypto_trader_tpu_portfolio_value_usd 12345.0" in text
        assert "request_latency_seconds_count" in text

    def test_histogram_buckets_valid(self):
        """+Inf cumulative bucket must equal _count (Prometheus contract)."""
        m = MetricsRegistry()
        for v in (0.003, 0.003, 0.2):
            m.observe("lat", v)
        text = m.exposition()
        inf_line = [l for l in text.splitlines() if 'le="+Inf"' in l][0]
        count_line = [l for l in text.splitlines() if l.startswith(
            "crypto_trader_tpu_lat_count")][0]
        assert inf_line.rsplit(" ", 1)[1] == count_line.rsplit(" ", 1)[1] == "3"


class TestCheckpoint:
    def test_roundtrip(self, tmp_path):
        tree = {"params": {"w": np.ones((3, 2)), "b": np.zeros(2)},
                "step": np.asarray(7)}
        p = save_checkpoint(str(tmp_path / "ckpt"), tree, {"note": "hi"})
        loaded, meta = load_checkpoint(p)
        np.testing.assert_allclose(loaded["params"]["w"], 1.0)
        assert int(loaded["step"]) == 7 and meta["note"] == "hi"


class TestPipeline:
    """monitor → analyzer → executor on the fake exchange, virtual clock."""

    @pytest.mark.slow
    def test_end_to_end_trade_flow(self):
        async def go():
            clock = VirtualClock()
            bus = EventBus(now_fn=clock)
            ex = FakeExchange({"BTCUSDC": _series(seed=12)}, quote_balance=10_000)
            mon = MarketMonitor(bus, ex, symbols=["BTCUSDC"], now_fn=clock,
                                kline_limit=128)
            ana = SignalAnalyzer(bus, now_fn=clock, analysis_interval_s=0.0)
            # permissive gates so the synthetic series actually trades
            execu = TradeExecutor(
                bus, ex, now_fn=clock,
                trading=TradingParams(ai_confidence_threshold=0.0,
                                      min_signal_strength=0.0))
            executed = 0
            for step in range(300):
                ex.advance("BTCUSDC")
                clock.t += 60.0
                await mon.poll()
                await ana.run_once()
                executed += await execu.run_once()
                # trailing stop maintenance on every tick
                px = ex.get_ticker("BTCUSDC")["price"]
                await execu.on_price("BTCUSDC", px)
            # first kline_limit-1 polls lack a full window (fixed-shape rule)
            assert bus.published_counts["market_updates"] > 100
            assert bus.published_counts["trading_signals"] > 100
            # at least one trade opened end-to-end through the bus
            assert executed >= 1
            assert len(ex.fills) >= 1
            return executed

        asyncio.run(go())

    def test_gates_block_low_confidence(self):
        async def go():
            bus = EventBus()
            ex = FakeExchange({"BTCUSDC": _series()})
            execu = TradeExecutor(bus, ex)
            out = await execu.handle_signal({
                "symbol": "BTCUSDC", "current_price": 100.0, "signal": "BUY",
                "decision": "BUY", "confidence": 0.3, "signal_strength": 90.0,
                "volatility": 0.02, "avg_volume": 1e6})
            assert out is None
            out = await execu.handle_signal({
                "symbol": "BTCUSDC", "current_price": 100.0, "signal": "BUY",
                "decision": "SELL", "confidence": 0.9, "signal_strength": 90.0,
                "volatility": 0.02, "avg_volume": 1e6})
            assert out is None
        asyncio.run(go())

    def test_trade_opens_with_protective_orders(self):
        async def go():
            bus = EventBus()
            ex = FakeExchange({"BTCUSDC": _series()}, quote_balance=10_000)
            execu = TradeExecutor(bus, ex)
            trade = await execu.handle_signal({
                "symbol": "BTCUSDC",
                "current_price": ex.get_ticker("BTCUSDC")["price"],
                "signal": "BUY", "decision": "BUY", "confidence": 0.95,
                "signal_strength": 85.0, "volatility": 0.02, "avg_volume": 1e6})
            assert trade is not None
            assert len(ex.open_orders) == 2          # stop + take-profit
            assert trade.stop_loss_pct > 0
            # trailing ratchet replaces the stop order on a strong move up
            old_stop_id = trade.stop_order_id
            await execu.on_price("BTCUSDC", trade.entry_price * 1.05)
            assert execu.active_trades["BTCUSDC"].stop_order_id != old_stop_id
        asyncio.run(go())

    def test_max_positions_cap(self):
        async def go():
            bus = EventBus()
            series = {f"S{i}USDC": _series(seed=i, symbol=f"S{i}USDC") for i in range(7)}
            ex = FakeExchange(series, quote_balance=100_000)
            execu = TradeExecutor(bus, ex,
                                  trading=TradingParams(max_positions=2))
            opened = 0
            for i in range(7):
                sym = f"S{i}USDC"
                t = await execu.handle_signal({
                    "symbol": sym, "current_price": ex.get_ticker(sym)["price"],
                    "signal": "BUY", "decision": "BUY", "confidence": 0.95,
                    "signal_strength": 85.0, "volatility": 0.02,
                    "avg_volume": 1e6})
                opened += t is not None
            assert opened == 2
        asyncio.run(go())

    def test_tp_fill_reconciled_not_double_sold(self):
        """A server-side TP fill must finalize the trade instead of leaving
        it active and double-selling later."""
        async def go():
            bus = EventBus()
            s = _series()
            ex = FakeExchange({"BTCUSDC": s}, quote_balance=10_000, fee_rate=0.0)
            execu = TradeExecutor(bus, ex)
            trade = await execu.handle_signal({
                "symbol": "BTCUSDC",
                "current_price": ex.get_ticker("BTCUSDC")["price"],
                "signal": "BUY", "decision": "BUY", "confidence": 0.95,
                "signal_strength": 85.0, "volatility": 0.02, "avg_volume": 1e6})
            # march candles until one protective order fills
            for _ in range(500):
                ex.advance("BTCUSDC")
                if len(ex.open_orders) < 2:
                    break
            assert len(ex.open_orders) < 2, "a protective order should fill"
            base_before = ex.get_balances().get("BTC", 0.0)
            await execu.on_price("BTCUSDC", ex.get_ticker("BTCUSDC")["price"])
            assert "BTCUSDC" not in execu.active_trades
            assert execu.closed_trades[-1]["reason"] in ("Take Profit", "Stop Loss")
            # no second market sell happened
            np.testing.assert_allclose(ex.get_balances().get("BTC", 0.0),
                                       base_before, atol=1e-9)
            assert not ex.open_orders     # sibling canceled
        asyncio.run(go())

    def test_close_trade_after_server_side_fill_finalizes(self):
        """A protective order that filled server-side must finalize the
        trade when close_trade races it — not strand it in active_trades
        with re-placed protective sells for inventory no longer held."""
        async def go():
            bus = EventBus()
            ex = FakeExchange({"BTCUSDC": _series()}, quote_balance=10_000)
            execu = TradeExecutor(bus, ex)
            await execu.handle_signal({
                "symbol": "BTCUSDC",
                "current_price": ex.get_ticker("BTCUSDC")["price"],
                "signal": "BUY", "decision": "BUY", "confidence": 0.95,
                "signal_strength": 85.0, "volatility": 0.02, "avg_volume": 1e6})
            for _ in range(500):
                ex.advance("BTCUSDC")
                if len(ex.open_orders) < 2:
                    break
            assert len(ex.open_orders) < 2, "a protective order should fill"
            base_before = ex.get_balances().get("BTC", 0.0)
            # close directly (e.g. trailing trigger) without an on_price
            # reconcile pass first
            await execu.close_trade(
                "BTCUSDC", ex.get_ticker("BTCUSDC")["price"], "Trailing Stop")
            assert "BTCUSDC" not in execu.active_trades
            assert execu.closed_trades[-1]["reason"] in ("Take Profit",
                                                         "Stop Loss")
            # no second market sell of already-sold inventory
            np.testing.assert_allclose(ex.get_balances().get("BTC", 0.0),
                                       base_before, atol=1e-9)
            assert not ex.open_orders
        asyncio.run(go())

    def test_close_trade_records_pnl(self):
        async def go():
            bus = EventBus()
            ex = FakeExchange({"BTCUSDC": _series()}, quote_balance=10_000)
            execu = TradeExecutor(bus, ex)
            trade = await execu.handle_signal({
                "symbol": "BTCUSDC",
                "current_price": ex.get_ticker("BTCUSDC")["price"],
                "signal": "BUY", "decision": "BUY", "confidence": 0.95,
                "signal_strength": 85.0, "volatility": 0.02, "avg_volume": 1e6})
            await execu.close_trade("BTCUSDC", trade.entry_price * 1.02, "Take Profit")
            assert not execu.active_trades
            rec = execu.closed_trades[-1]
            assert rec["reason"] == "Take Profit"
            np.testing.assert_allclose(
                rec["pnl"], trade.entry_price * 0.02 * trade.quantity, rtol=1e-5)
        asyncio.run(go())
