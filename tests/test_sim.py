"""Device-resident market simulator (ISSUE 7): scenario schedules, traced
paths, the traced matching engine, and the one-dispatch sweep.

The two contracts that guard the subsystem:

  * **Parity oracle** — a single-scenario rollout must match FakeExchange
    trade-by-trade (fills, fees, final equity) when driven through the
    identical strategy decisions on the same candle series (the
    `ops/tick_engine.py` oracle pattern);
  * **Sweep contract** — ≥ 4096 scenarios evaluate as ONE jitted dispatch
    with ONE host readback, zero recompiles at steady state, and a
    `sim_sweep` devprof cost card whose donated schedule buffers are
    verifiably freed (aliased onto the candle/equity outputs).

Plus fill-accounting property tests over random order flows: ledger
conservation (balances + fees ≡ the fill log), partial-fill carryover,
and same-seed determinism.
"""

import functools

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ai_crypto_trader_tpu.data.ingest import from_dict
from ai_crypto_trader_tpu.data.synthetic import generate_ohlcv, regime_chain
from ai_crypto_trader_tpu.shell.exchange import FakeExchange
from ai_crypto_trader_tpu.sim import engine, paths, scenarios
from ai_crypto_trader_tpu.sim import exchange as sx
from ai_crypto_trader_tpu.utils import devprof

f32 = np.float32


# --------------------------------------------------------------------------
# satellites: vectorized batched synthetic data, symbol-mixed book seeds
# --------------------------------------------------------------------------

class TestSyntheticBatch:
    def test_batch_rows_bit_identical_to_scalar_calls(self):
        batch = generate_ohlcv(n=400, seed=[3, 7, 11])
        for i, s in enumerate([3, 7, 11]):
            scalar = generate_ohlcv(n=400, seed=s)
            for k in scalar:
                assert np.array_equal(batch[k][i], scalar[k]), (k, s)

    def test_scalar_shape_unchanged(self):
        d = generate_ohlcv(n=256, seed=0)
        assert d["close"].shape == (256,) and d["regime"].shape == (256,)

    def test_regime_chain_matches_sequential_loop(self, rng):
        switches = rng.random(500) < 0.05
        choices = rng.integers(0, 3, size=500)
        state, expect = 0, np.empty(500, np.int64)
        for i in range(500):
            if switches[i]:
                state = choices[i]
            expect[i] = state
        np.testing.assert_array_equal(regime_chain(switches, choices), expect)

    def test_traced_regime_chain_matches_numpy(self, rng):
        switches = rng.random((4, 300)) < 0.03
        choices = rng.integers(0, 3, size=(4, 300))
        got = paths.regime_chain(jnp.asarray(switches),
                                 jnp.asarray(choices, jnp.int32))
        np.testing.assert_array_equal(np.asarray(got),
                                      regime_chain(switches, choices))


class TestOrderBookSeed:
    def test_symbols_get_distinct_books_at_same_cursor(self):
        n = 64
        d = generate_ohlcv(n=n, seed=1)
        series = {s: from_dict({k: v for k, v in d.items() if k != "regime"},
                               symbol=s) for s in ("AAAUSDC", "BBBUSDC")}
        ex = FakeExchange(series)
        ex.advance(steps=10)
        sizes = {s: [lvl[1] for lvl in ex.get_order_book(s)["bids"]]
                 for s in series}
        assert sizes["AAAUSDC"] != sizes["BBBUSDC"]
        # still deterministic per (symbol, cursor)
        again = [lvl[1] for lvl in ex.get_order_book("AAAUSDC")["bids"]]
        assert again == sizes["AAAUSDC"]


# --------------------------------------------------------------------------
# scenario schedules and traced paths
# --------------------------------------------------------------------------

class TestScenarios:
    def test_every_preset_compiles_and_is_deterministic(self):
        for name in scenarios.preset_names():
            a = scenarios.compile_schedules(name, 4, 128, seed=5)
            b = scenarios.compile_schedules(name, 4, 128, seed=5)
            for field in scenarios.ShockSchedule._fields:
                arr = getattr(a, field)
                assert arr.shape == (4, 128) and arr.dtype == np.float32
                np.testing.assert_array_equal(arr, getattr(b, field))

    def test_presets_actually_inject_their_pathology(self):
        crash = scenarios.compile_schedules("flash_crash", 8, 256, seed=1)
        assert crash.logret_shift.min() < -0.02
        hole = scenarios.compile_schedules("liquidity_hole", 8, 256, seed=1)
        assert hole.liquidity_mult.min() < 0.11
        outage = scenarios.compile_schedules("exchange_outage", 8, 256, seed=1)
        assert outage.halt.max() == 1.0
        blow = scenarios.compile_schedules("spread_blowout", 8, 256, seed=1)
        assert blow.spread.max() >= 0.002
        calm = scenarios.compile_schedules("calm", 8, 256, seed=1)
        assert calm.logret_shift.any() == 0 and calm.halt.any() == 0

    def test_mixed_round_robin_covers_all_presets(self):
        sched, labels = scenarios.mixed_schedules(None, 24, 64, seed=0)
        assert sched.num_scenarios == 24 and sched.steps == 64
        assert set(labels) == set(scenarios.preset_names())

    def test_mc_schedule_channels(self):
        shift, vol = scenarios.mc_schedule("flash_crash", 16, 29, seed=0)
        assert shift.shape == vol.shape == (16, 29)
        assert shift.min() < 0.0 and vol.max() > 1.0

    def test_unknown_preset_raises(self):
        with pytest.raises(KeyError, match="unknown scenario"):
            scenarios.preset("nope")


class TestPaths:
    def test_gbm_candle_structure_and_determinism(self):
        sched = scenarios.compile_schedules("flash_crash", 8, 256, seed=2)
        key = jax.random.PRNGKey(0)
        c = {k: np.asarray(v) for k, v in paths.gbm_candles(key, sched).items()}
        assert c["close"].shape == (8, 256)
        assert (c["high"] >= np.maximum(c["open"], c["close"]) - 1e-2).all()
        assert (c["low"] <= np.minimum(c["open"], c["close"]) + 1e-2).all()
        assert (c["low"] > 0).all() and (c["volume"] > 0).all()
        assert np.isin(c["regime"], [0, 1, 2]).all()
        c2 = {k: np.asarray(v) for k, v in paths.gbm_candles(key, sched).items()}
        for k in c:
            np.testing.assert_array_equal(c[k], c2[k])

    def test_crash_schedule_moves_prices(self):
        calm = scenarios.compile_schedules("calm", 8, 256, seed=3)
        crash = scenarios.compile_schedules("flash_crash", 8, 256, seed=3)
        key = jax.random.PRNGKey(1)
        c_calm = np.asarray(paths.gbm_candles(key, calm)["close"])
        c_crash = np.asarray(paths.gbm_candles(key, crash)["close"])
        # same key → same diffusion; the crash overlay must bite
        drop_calm = c_calm.min(axis=1) / 40_000.0
        drop_crash = c_crash.min(axis=1) / 40_000.0
        assert (drop_crash < drop_calm - 0.02).any()

    def test_bootstrap_candles(self, rng):
        rets = jnp.asarray(rng.normal(0, 0.002, 512), jnp.float32)
        sched = scenarios.compile_schedules("vol_regime_shift", 4, 128, seed=0)
        c = paths.bootstrap_candles(jax.random.PRNGKey(2), rets, sched)
        close = np.asarray(c["close"])
        assert close.shape == (4, 128) and (close > 0).all()


# --------------------------------------------------------------------------
# fill-accounting property tests over random order flows
# --------------------------------------------------------------------------

K_FLOW, L_FLOW = 4, 1024


@functools.partial(jax.jit, static_argnames=())
def _run_flow(candles, actions, quote0, fee_rate, cap):
    """Drive the bare exchange through arbitrary action streams."""

    def one(c_scen, a_scen):
        def step(st, xs):
            a, candle, t = xs
            st = sx.settle_pending(st, candle, t, fee_rate,
                                   jnp.asarray(0.0), jnp.asarray(0.0))
            st = sx.match_candle(st, candle, t, cap, jnp.asarray(0.0),
                                 fee_rate)
            st = sx.apply_action(st, candle, t, a, fee_rate,
                                 jnp.asarray(0.0), jnp.asarray(0.0),
                                 jnp.asarray(0.0))
            return st, None

        T = c_scen["close"].shape[0]
        st0 = sx.init_state(quote0, K=K_FLOW, L=L_FLOW)
        st, _ = jax.lax.scan(
            step, st0, (a_scen, c_scen, jnp.arange(T, dtype=jnp.int32)))
        return st

    return jax.vmap(one)(candles, actions)


def _random_flow(rng, B, T, close):
    """Seeded random order flow: markets, placements (some at absurd
    prices/sizes so rejects and never-triggering orders are exercised),
    and cancels."""
    mk = rng.random((B, T)) < 0.15
    qty = np.exp(rng.normal(-3.5, 1.2, (B, T))).astype(f32)
    place = (rng.random((B, T, K_FLOW)) < 0.10)
    side = rng.choice([sx.BUY, sx.SELL], (B, T, K_FLOW)).astype(np.int32)
    kind = rng.choice([sx.LIMIT, sx.STOP], (B, T, K_FLOW)).astype(np.int32)
    slot_qty = np.exp(rng.normal(-3.0, 1.5, (B, T, K_FLOW))).astype(f32)
    ref = close[:, :, None]
    limit_price = (ref * (1.0 + rng.normal(0, 0.02, (B, T, K_FLOW)))).astype(f32)
    stop_price = (ref * (1.0 + rng.normal(0, 0.02, (B, T, K_FLOW)))).astype(f32)
    return sx.Action(
        market_qty=np.where(mk, qty, 0.0).astype(f32),
        market_side=rng.choice([sx.BUY, sx.SELL], (B, T)).astype(np.int32),
        cancel=rng.random((B, T, K_FLOW)) < 0.05,
        place=place, side=side, kind=kind, qty=slot_qty,
        limit_price=limit_price, stop_price=stop_price)


class TestFillAccounting:
    B, T = 16, 128

    def _flow_state(self, seed=0, fee=0.001, cap=np.inf, q0=1_000.0):
        d = generate_ohlcv(n=self.T, seed=list(range(100, 100 + self.B)))
        candles = {k: jnp.asarray(d[k]) for k in
                   ("open", "high", "low", "close")}
        actions = jax.tree.map(
            jnp.asarray,
            _random_flow(np.random.default_rng(seed), self.B, self.T,
                         d["close"]))
        st = _run_flow(candles, actions, jnp.asarray(q0, jnp.float32),
                       jnp.asarray(fee, jnp.float32),
                       jnp.asarray(cap, jnp.float32))
        return jax.device_get(st), q0

    def test_ledger_conservation_balances_and_fees_match_fill_log(self):
        st, q0 = self._flow_state()
        assert (st.n_fills > 0).sum() >= self.B // 2, "flow barely trades"
        assert (st.dropped_fills == 0).all()
        for b in range(self.B):
            log = st.fills[b][:int(st.n_fills[b])].astype(np.float64)
            side, qty, price, fee = log[:, 2], log[:, 3], log[:, 4], log[:, 5]
            buys, sells = side > 0, side < 0
            cost = qty * price
            quote_expect = (q0 - (cost[buys] + fee[buys]).sum()
                            + (cost[sells] - fee[sells]).sum())
            base_expect = qty[buys].sum() - qty[sells].sum()
            np.testing.assert_allclose(st.quote[b], quote_expect,
                                       rtol=1e-5, atol=5e-2)
            np.testing.assert_allclose(st.base[b], base_expect,
                                       rtol=1e-4, atol=1e-5)
            np.testing.assert_allclose(st.fee_paid[b], fee.sum(),
                                       rtol=1e-4, atol=1e-3)
            # fees are consistent with prices at the booked rate
            np.testing.assert_allclose(fee, cost * 0.001, rtol=1e-3,
                                       atol=1e-6)

    def test_no_negative_balances_ever_booked(self):
        for seed in (0, 1, 2):
            st, _ = self._flow_state(seed=seed)
            assert (st.quote >= -1e-3).all()
            assert (st.base >= -1e-6).all()

    def test_same_seed_flows_are_bit_deterministic(self):
        a, _ = self._flow_state(seed=3)
        b, _ = self._flow_state(seed=3)
        for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y))

    def test_partial_fill_carryover_under_liquidity_cap(self):
        # constant candles; one resting LIMIT BUY for 10 base, cap 3/candle
        T = 6
        const = np.full((1, T), 100.0, f32)
        candles = {k: jnp.asarray(v) for k, v in
                   {"open": const, "high": const * 1.01,
                    "low": const * 0.99, "close": const}.items()}
        act = jax.tree.map(lambda x: jnp.asarray(x)[None],
                           sx.no_action(K_FLOW))
        act = jax.tree.map(lambda x: jnp.repeat(x[:, None], T, axis=1), act)
        place = np.zeros((1, T, K_FLOW), bool)
        place[0, 0, 0] = True
        act = act._replace(
            place=jnp.asarray(place),
            side=jnp.full((1, T, K_FLOW), sx.BUY, jnp.int32),
            kind=jnp.full((1, T, K_FLOW), sx.LIMIT, jnp.int32),
            qty=jnp.full((1, T, K_FLOW), 10.0, jnp.float32),
            limit_price=jnp.full((1, T, K_FLOW), 100.0, jnp.float32))
        st = jax.device_get(_run_flow(
            candles, act, jnp.asarray(10_000.0, jnp.float32),
            jnp.asarray(0.0, jnp.float32), jnp.asarray(3.0, jnp.float32)))
        log = st.fills[0][:int(st.n_fills[0])]
        np.testing.assert_allclose(log[:, 3], [3.0, 3.0, 3.0, 1.0])
        np.testing.assert_allclose(st.base[0], 10.0)
        assert not bool(st.book.active[0][0])      # fully consumed
        assert int(st.n_fills[0]) == 4


# --------------------------------------------------------------------------
# the parity oracle: sim rollout ≡ FakeExchange, trade by trade
# --------------------------------------------------------------------------

def _oracle_run(c: dict, liq_mult, fee, cap, q0, T,
                strat: engine.SimStrategy):
    """Drive FakeExchange through the EXACT decision rule of
    `engine._strategy_step` (f32 arithmetic mirrored), returning the fill
    sequence, final equity and total fees."""
    al_f = f32(np.asarray(strat.alpha_fast))
    al_s = f32(np.asarray(strat.alpha_slow))
    margin = f32(np.asarray(strat.entry_margin))
    sl = f32(np.asarray(strat.sl_pct))
    tp = f32(np.asarray(strat.tp_pct))
    frac = f32(np.asarray(strat.trade_frac))
    min_not = float(np.asarray(strat.min_notional))

    series = from_dict({k: c[k] for k in
                        ("open", "high", "low", "close", "volume")},
                       symbol="SIMUSDC")
    ex = FakeExchange({"SIMUSDC": series}, quote_balance=q0, fee_rate=fee,
                      max_fill_base=cap)
    ema_f = ema_s = f32(0.0)
    entry = f32(0.0)
    fills, seen = [], [0]

    def drain(t):
        for fd in ex.fills[seen[0]:]:
            fills.append((t, 1 if fd["side"] == "BUY" else -1,
                          fd["quantity"], fd["price"], fd["fee"]))
        seen[0] = len(ex.fills)

    for t in range(T):
        # the schedule's per-candle liquidity cap, venue-side
        ex.max_fill_base = float(f32(cap) * f32(liq_mult[t]))
        if t > 0:
            ex.advance()
        drain(t)
        close = c["close"][t]
        bal = ex.get_balances()
        quote, base = bal.get("USDC", 0.0), bal.get("SIM", 0.0)
        if t == 0:
            ema_f = ema_s = f32(close)
        else:
            ema_f = f32(ema_f + al_f * f32(close - ema_f))
            ema_s = f32(ema_s + al_s * f32(close - ema_s))
        flat = base * float(close) < min_not
        resting = ex.list_open_orders("SIMUSDC")
        if flat and resting:                      # post-exit sibling cleanup
            for o in resting:
                ex.cancel_order("SIMUSDC", o["order_id"])
            resting = []
        cross = ema_f > f32(ema_s * f32(1.0 + margin))
        if flat and not resting and cross and t >= engine.WARMUP:
            qty = f32(f32(frac * f32(quote)) / close)
            ex.place_order("SIMUSDC", "BUY", "MARKET", float(qty))
            entry = f32(close)
            drain(t)
        elif not flat and not resting:            # protective stop + TP
            sp = f32(entry * f32(1.0 - f32(sl / f32(100.0))))
            tpp = f32(entry * f32(1.0 + f32(tp / f32(100.0))))
            ex.place_order("SIMUSDC", "SELL", "STOP_LOSS", float(base),
                           stop_price=float(sp))
            ex.place_order("SIMUSDC", "SELL", "LIMIT", float(base),
                           price=float(tpp))
    bal = ex.get_balances()
    eq = bal.get("USDC", 0.0) + bal.get("SIM", 0.0) * float(c["close"][-1])
    return fills, eq, sum(fd["fee"] for fd in ex.fills)


class TestParityOracle:
    """The acceptance contract: a single-scenario run reproduces
    FakeExchange trade-by-trade on the same candle series."""

    @pytest.mark.parametrize("preset,seed", [
        ("flash_crash", 3),        # crash → stops fire, multiple roundtrips
        ("vol_regime_shift", 5),   # busy two-sided tape
        ("liquidity_hole", 9),     # capped fills → partial carryover
        ("calm", 7),               # quiet market, few trades
    ])
    def test_single_scenario_matches_fake_exchange(self, preset, seed):
        T = 768
        sched = scenarios.compile_schedules(preset, 1, T, seed=seed)
        candles = {k: np.asarray(v) for k, v in
                   paths.gbm_candles(jax.random.PRNGKey(seed), sched).items()}
        strat = engine.default_strategy(sl_pct=1.0, tp_pct=1.5)
        fee, cap, q0 = 0.001, 0.02, 10_000.0
        out = engine.rollout_candles(
            candles, schedule=sched, strategy=strat,
            fills_params=engine.fill_params(fee_rate=fee, max_fill_base=cap),
            quote_balance=q0)
        s = out["summary"]
        n = int(s["n_fills"][0])
        assert s["dropped_fills"][0] == 0
        sim_fills = out["fills"][0][:n]

        c1 = {k: candles[k][0] for k in candles}
        oracle_fills, oracle_eq, oracle_fees = _oracle_run(
            c1, np.asarray(sched.liquidity_mult[0]), fee, cap, q0, T, strat)

        assert n == len(oracle_fills), \
            f"{preset}: sim {n} fills vs oracle {len(oracle_fills)}"
        for srow, orow in zip(sim_fills, oracle_fills):
            t_s, _tag, side_s, qty_s, price_s, fee_s = map(float, srow)
            t_o, side_o, qty_o, price_o, fee_o = orow
            assert (t_s, side_s) == (t_o, side_o), (srow, orow)
            np.testing.assert_allclose(qty_s, qty_o, rtol=1e-4, atol=1e-9)
            np.testing.assert_allclose(price_s, price_o, rtol=1e-5)
            np.testing.assert_allclose(fee_s, fee_o, rtol=1e-3, atol=1e-6)
        np.testing.assert_allclose(float(s["fees"][0]), oracle_fees,
                                   rtol=1e-4, atol=1e-3)
        np.testing.assert_allclose(float(s["final_equity"][0]), oracle_eq,
                                   rtol=1e-4)

    def test_parity_fills_actually_happen(self):
        """Guard the oracle itself: the crash scenario must produce a
        non-trivial trade count or the parity test proves nothing."""
        T = 768
        sched = scenarios.compile_schedules("flash_crash", 1, T, seed=3)
        candles = {k: np.asarray(v) for k, v in
                   paths.gbm_candles(jax.random.PRNGKey(3), sched).items()}
        out = engine.rollout_candles(
            candles, schedule=sched,
            strategy=engine.default_strategy(sl_pct=1.0, tp_pct=1.5),
            fills_params=engine.fill_params(fee_rate=0.001,
                                            max_fill_base=0.02))
        assert int(out["summary"]["n_fills"][0]) >= 10


# --------------------------------------------------------------------------
# the sweep contract: ≥4096 scenarios, one dispatch, zero recompiles
# --------------------------------------------------------------------------

class TestSweepContract:
    def test_4096_scenarios_one_dispatch_zero_recompile(self, monkeypatch):
        from ai_crypto_trader_tpu.utils import meshprof
        from ai_crypto_trader_tpu.utils.metrics import MetricsRegistry

        B, T = 4096, 256
        syncs = {"n": 0}
        real_read = engine.host_read

        def counting_read(tree):
            syncs["n"] += 1
            return real_read(tree)

        monkeypatch.setattr(engine, "host_read", counting_read)
        m = MetricsRegistry()
        # the zero-recompile assertion rides the meshprof RecompileSentinel
        # — the same watch-window counter production pages on
        mp = meshprof.MeshProf()
        with devprof.use(devprof.DevProf(metrics=m)) as dp, \
                meshprof.use(mp):
            out = engine.sweep(jax.random.PRNGKey(0), scenario="mixed",
                               num_scenarios=B, steps=T)   # compile + card
            assert syncs["n"] == 1
            assert out["stats"]["dispatches"] == 1
            assert out["stats"]["scenarios"] == B
            assert out["summary"]["final_equity"].shape == (B,)
            assert len(out["labels"]) == B
            # cost card + donation check (acceptance criteria)
            card = dp.cards["sim_sweep"]
            assert card.error is None and card.flops > 0
            assert card.donation_ok is True
            assert dp.donation_failures == []
            # the big outputs stayed on device — the one sync is [B]-sized
            assert out["device"]["candles"]["close"].shape == (B, T)

            out2 = engine.sweep(jax.random.PRNGKey(1), scenario="mixed",
                                num_scenarios=B, steps=T, seed=1)
            assert mp.recompiles.steady_total() == 0, \
                mp.recompiles.status()             # zero recompiles: preset
            #                                        changes are array
            #                                        CONTENT, not programs
            assert mp.recompiles.windows["sim_sweep"] == 2
            assert mp.transfers.total() == 0       # no unsanctioned pulls
            assert syncs["n"] == 2                 # ONE more host readback
        # different keys/schedules → different outcomes (not a cached blob)
        assert not np.array_equal(out["summary"]["final_equity"],
                                  out2["summary"]["final_equity"])

    def test_sweep_same_seed_deterministic(self):
        a = engine.sweep(jax.random.PRNGKey(5), scenario="flash_crash",
                         num_scenarios=32, steps=128, seed=2)
        b = engine.sweep(jax.random.PRNGKey(5), scenario="flash_crash",
                         num_scenarios=32, steps=128, seed=2)
        for k, v in a["summary"].items():
            np.testing.assert_array_equal(v, b["summary"][k], err_msg=k)

    def test_adversarial_presets_hurt_more_than_calm(self):
        kw = dict(num_scenarios=48, steps=256, seed=4,
                  strategy=engine.default_strategy(sl_pct=1.0, tp_pct=1.5))
        calm = engine.sweep(jax.random.PRNGKey(9), scenario="calm", **kw)
        swan = engine.sweep(jax.random.PRNGKey(9), scenario="black_swan",
                            **kw)
        # the black swan batch must show strictly worse tails
        assert (swan["summary"]["min_equity"].min()
                < calm["summary"]["min_equity"].min())
        assert (swan["summary"]["max_drawdown"].max()
                > calm["summary"]["max_drawdown"].max())


# --------------------------------------------------------------------------
# workload integrations: mc stress-VaR, backtest-under-stress, RL env
# --------------------------------------------------------------------------

class TestMcStress:
    def test_unstressed_path_parity_pinned(self, rng):
        """stress=None must trace to the exact pre-stress program: pin the
        full stats block against a manual re-composition."""
        from ai_crypto_trader_tpu import mc

        key = jax.random.PRNGKey(11)
        rets = rng.normal(0.0005, 0.02, 500).astype(np.float32)
        out = mc.run_simulation(key, 100.0, rets, days=30, num_sims=256)
        mu, sigma = mc.estimate_mu_sigma(jnp.asarray(rets))
        paths_ref = mc.simulate_gbm(key, 100.0, mu, sigma, 30, 256)
        ref = mc.path_statistics(paths_ref, 100.0, 0.95)
        np.testing.assert_array_equal(np.asarray(out["paths"]),
                                      np.asarray(paths_ref))
        np.testing.assert_array_equal(np.asarray(out["var"]),
                                      np.asarray(ref["var"]))
        assert out["stress"] is None

    def test_stress_mode_fattens_the_left_tail(self, rng):
        from ai_crypto_trader_tpu import mc

        key = jax.random.PRNGKey(12)
        rets = rng.normal(0.0005, 0.01, 500).astype(np.float32)
        kw = dict(days=30, num_sims=2048)
        base = mc.run_simulation(key, 100.0, rets, **kw)
        crash = mc.run_simulation(key, 100.0, rets, stress="flash_crash",
                                  **kw)
        assert crash["stress"] == "flash_crash"
        assert float(crash["var"]) < float(base["var"])      # var is signed pct
        assert float(crash["cvar"]) < float(base["cvar"])
        assert (float(crash["max_drawdown_mean"])
                > float(base["max_drawdown_mean"]))

    def test_bootstrap_stress_mode(self, rng):
        from ai_crypto_trader_tpu import mc

        key = jax.random.PRNGKey(13)
        rets = rng.normal(0.0, 0.01, 400).astype(np.float32)
        out = mc.run_simulation(key, 100.0, rets, days=20, num_sims=512,
                                method="bootstrap", stress="black_swan")
        assert np.asarray(out["paths"]).shape == (512, 20)

    def test_stress_var_cvar_report(self, rng):
        from ai_crypto_trader_tpu import risk

        rets = rng.normal(0.0005, 0.01, 500).astype(np.float32)
        rep = risk.stress_var_cvar(jax.random.PRNGKey(14), 100.0, rets,
                                   stress="flash_crash", days=30,
                                   num_sims=1024)
        assert rep["stress"] == "flash_crash"
        assert rep["stress_var_pct"] >= rep["var_pct"]
        assert rep["stress_cvar_pct"] >= rep["stress_var_pct"]
        # uplift is the SIGNED tail shift, immune to the positive-loss clamp
        assert rep["var_uplift_pct"] == pytest.approx(
            rep["var_signed_pct"] - rep["stress_var_signed_pct"])
        assert rep["var_uplift_pct"] > 0


class TestBacktestUnderStress:
    def test_scenario_batch_stats(self):
        stats, summary = engine.backtest_under_stress(
            jax.random.PRNGKey(20), scenario=["calm", "flash_crash"],
            num_scenarios=8, steps=512)
        assert np.asarray(stats.final_balance).shape == (8,)
        assert summary["final_balance_p05"] <= summary["final_balance_p95"]
        assert summary["worst_final_balance"] > 0
        assert len(summary["labels"]) == 8

    def test_population_axis(self):
        from ai_crypto_trader_tpu.backtest import sample_params

        params = sample_params(jax.random.PRNGKey(0), 4)
        stats, _ = engine.backtest_under_stress(
            jax.random.PRNGKey(21), scenario="flash_crash",
            num_scenarios=6, steps=512, params=params)
        assert np.asarray(stats.final_balance).shape == (6, 4)


class TestScenarioRLEnv:
    def test_env_params_carry_scenario_axis(self):
        from ai_crypto_trader_tpu.rl import env_reset, env_step

        p, labels = engine.scenario_env_params(
            jax.random.PRNGKey(30), scenario=["calm", "flash_crash"],
            num_scenarios=4, steps=512, episode_len=32)
        assert p.close.shape == (4, 512)
        assert p.obs_table.shape == (4, 512, 8)
        assert len(labels) == 4
        keys = jax.random.split(jax.random.PRNGKey(0), 64)
        states, obs = jax.vmap(lambda k: env_reset(p, k))(keys)
        scen = np.asarray(states.scen)
        assert obs.shape == (64, 10)
        assert scen.min() >= 0 and scen.max() <= 3
        assert len(np.unique(scen)) > 1            # actually samples lanes
        s2, obs2, r, done = jax.vmap(
            lambda s: env_step(p, s, jnp.asarray(1)))(states)
        assert obs2.shape == (64, 10)
        np.testing.assert_array_equal(np.asarray(s2.scen), scen)

    def test_single_path_env_unchanged(self, ohlcv):
        from ai_crypto_trader_tpu import ops
        from ai_crypto_trader_tpu.rl import env_reset, env_step, make_env_params
        from ai_crypto_trader_tpu.rl.env import BUY

        arrays = {k: jnp.asarray(v[:512]) for k, v in ohlcv.items()
                  if k != "regime"}
        p = make_env_params(ops.compute_indicators(arrays), episode_len=64)
        s, obs = env_reset(p, jax.random.PRNGKey(0))
        assert obs.shape == (10,) and int(s.scen) == 0
        t0 = int(s.t)
        s, _, r, _ = env_step(p, s, jnp.asarray(BUY))
        expect = ((float(p.close[t0 + 1]) - float(p.close[t0]))
                  / float(p.close[t0]))
        np.testing.assert_allclose(float(r), expect, rtol=1e-5)


# --------------------------------------------------------------------------
# slow tier: the full-scale sweep and scenario-diverse DQN training
# --------------------------------------------------------------------------

@pytest.mark.slow
class TestFullScaleSweep:
    def test_10k_scenarios_single_dispatch(self):
        out = engine.sweep(jax.random.PRNGKey(0), scenario="mixed",
                           num_scenarios=10_000, steps=1024)
        s = out["summary"]
        assert s["final_equity"].shape == (10_000,)
        assert np.isfinite(s["final_equity"]).all()
        assert out["stats"]["dispatches"] == 1
        assert (s["n_fills"] > 0).mean() > 0.2      # the market gets traded
        # the fill log is a bounded ring: a busy tail scenario may overflow
        # it (counted, balances unaffected), but it must stay a tail event
        assert (s["dropped_fills"] > 0).mean() < 0.05

    def test_dqn_trains_on_scenario_env(self):
        from ai_crypto_trader_tpu.rl import DQNConfig, dqn_init, train_iterations

        p, _ = engine.scenario_env_params(
            jax.random.PRNGKey(40), scenario="mixed", num_scenarios=16,
            steps=768, episode_len=128)
        cfg = DQNConfig(num_envs=32, rollout_len=8)
        st = dqn_init(jax.random.PRNGKey(1), p, cfg)
        st, metrics = train_iterations(p, st, cfg, n_iters=4)
        assert np.isfinite(np.asarray(metrics["loss"])).all()
        # envs really spread across scenario lanes
        assert len(np.unique(np.asarray(st.env_states.scen))) > 1
