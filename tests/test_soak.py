"""Long-run paper-trading soak (VERDICT r4 next#5): the FULL launcher —
monitor/analyzer/executor + social/news/patterns/regime/NN/evolver/
generator/grid/DCA + the dashboard server — driven for thousands of
virtual ticks on FakeExchange.  The reference's product is a long-running
process (`run_trader.py:1326-1494`); this pins sustained multi-service
operation: no unhandled errors, every heartbeat advances, the books
reconcile against the exchange ledger, and the dashboard still renders.

Slow tier: run with `pytest -m slow tests/test_soak.py`.
"""

import asyncio

import numpy as np
import pytest

from ai_crypto_trader_tpu.config import (EvolutionParams, GAParams,
                                         TradingParams)
from ai_crypto_trader_tpu.data.ingest import from_dict
from ai_crypto_trader_tpu.data.synthetic import generate_ohlcv
from ai_crypto_trader_tpu.shell.dashboard_server import DashboardServer
from ai_crypto_trader_tpu.shell.exchange import FakeExchange
from ai_crypto_trader_tpu.shell.launcher import TradingSystem
from ai_crypto_trader_tpu.shell.stack import build_full_stack
from ai_crypto_trader_tpu.strategy.registry import ModelRegistry

# Slow tier (VERDICT r4 next#3): golden-parity / end-to-end /
# training / sharded-compile suite — deselected by the default
# run, executed via `pytest -m slow`.
pytestmark = pytest.mark.slow


TICKS = 2_000
SYMBOLS = ("BTCUSDC", "ETHUSDC")


def test_full_stack_soak(tmp_path):
    n = TICKS + 700
    series = {s: from_dict(generate_ohlcv(n=n, seed=21 + i), symbol=s)
              for i, s in enumerate(SYMBOLS)}
    clock = {"t": 0.0}
    ex = FakeExchange(series, quote_balance=100_000.0, fee_rate=0.0)
    ex.advance(steps=600)              # warm history for the fixed window
    system = TradingSystem(ex, list(SYMBOLS), now_fn=lambda: clock["t"],
                           dashboard_path=str(tmp_path / "dash.html"),
                           enable_devprof=True)
    # permissive gates so the loop actually trades during the soak
    system.executor.trading = TradingParams(ai_confidence_threshold=0.0,
                                            min_signal_strength=0.0,
                                            max_positions=2)
    registry = ModelRegistry(path=str(tmp_path / "registry.json"))
    system.registry = registry
    services = build_full_stack(
        system, registry=registry,
        grid_symbol="BTCUSDC", dca_symbol="ETHUSDC",
        cadences={
            # every service must FIRE repeatedly inside the soak window,
            # with budgets sized for a test (the production defaults are
            # hours-scale)
            "social": {"cache_ttl_s": 120.0},
            "news": {"poll_interval_s": 300.0},
            "patterns": {"update_interval_s": 300.0,
                         "report_interval_s": 600.0,
                         "checkpoint": str(tmp_path / "pattern_cnn"),
                         "train_kwargs": {"epochs": 1, "n_per_class": 4}},
            "regime": {"interval_s": 600.0, "retrain_interval_s": 1e9},
            "nn": {"epochs": 1, "units": 8, "hpo_trials": 0,
                   "retrain_interval_s": 1e9, "intervals": ("1m",),
                   "seq_len": 30},
            "evolver": {"interval_s": 20_000.0, "min_candles": 128},
            "evolution_cfg": EvolutionParams(
                method="ga", ga=GAParams(population_size=8, generations=2)),
            "generator": {"interval_s": 30_000.0, "min_candles": 700,
                          "pool_size": 4, "max_rounds": 1, "cv_folds": 2},
            "grid": {"order_size": 200.0, "lookback": 200},
            "dca": {"base_amount": 150.0, "interval_s": 7_200.0,
                    "rebalance_targets": {"ETH": 0.5, "USDC": 0.5},
                    "rebalance_interval_s": 40_000.0},
        })
    server = DashboardServer(system, port=0).start()

    service_errors = []
    q_alerts = system.bus.subscribe("alerts")

    async def go():
        for _ in range(TICKS):
            ex.advance()
            clock["t"] += 60.0
            await system.tick()
            while not q_alerts.empty():
                msg = q_alerts.get_nowait()["data"]
                if msg.get("name") == "ServiceError":
                    service_errors.append(msg)
        # one reconciling tick at the SAME candle: a protective SELL that
        # matched inside the loop's final ex.advance() is only folded into
        # the executor's books by the next on_price pass
        await system.tick()
        while not q_alerts.empty():
            msg = q_alerts.get_nowait()["data"]
            if msg.get("name") == "ServiceError":
                service_errors.append(msg)
        return system.status_cached()

    try:
        status = asyncio.run(go())

        # 1. no unhandled service errors across the whole soak
        assert service_errors == [], service_errors[:3]

        # 2. every registered service heartbeated, and recently
        beats = system.heartbeats.beats
        for svc in services:
            assert svc.name in beats, f"{svc.name} never heartbeated"
            assert clock["t"] - beats[svc.name] <= 60.0, \
                f"{svc.name} heartbeat stale"
        for core in ("monitor", "analyzer", "executor"):
            assert clock["t"] - beats[core] <= 60.0

        # 3. the loop actually traded, and the services actually fired
        counts = system.bus.published_counts
        assert counts["market_updates"] >= 2 * TICKS * 0.9
        assert counts["trading_signals"] > 0
        assert counts["social_updates"] > 5
        assert counts["news_updates"] > 2
        assert counts["regime_updates"] > 1
        assert counts.get("strategy_update", 0) >= 1     # evolver hot swap
        assert status["closed_trades"] + len(status["active_trades"]) > 0
        assert len(ex.fills) > 0

        # 4. books reconcile against the exchange ledger:
        #    (a) the fake's balances re-derive exactly from its fill log
        derived = {"USDC": 100_000.0}
        for f in ex.fills:
            base = f["symbol"][:-4]
            cost = f["quantity"] * f["price"]
            if f["side"] == "BUY":
                derived["USDC"] = derived.get("USDC", 0.0) - cost
                derived[base] = derived.get(base, 0.0) + f["quantity"]
            else:
                derived["USDC"] = derived.get("USDC", 0.0) + cost
                derived[base] = derived.get(base, 0.0) - f["quantity"]
        for asset, v in ex.get_balances().items():
            np.testing.assert_allclose(v, derived.get(asset, 0.0),
                                       rtol=1e-9, atol=1e-6)
        #    (b) every open executor position is backed by real inventory.
        #    ETH is exempt from the strict check: the DCA rebalancer SELLs
        #    drift on the same shared account (faithful to the reference's
        #    one-Binance-account topology), which can consume backing.
        for sym, trade in system.executor.active_trades.items():
            if sym == "BTCUSDC":
                assert (ex.get_balances().get("BTC", 0.0)
                        >= trade.quantity - 1e-9)
        #    (c) nothing went negative
        assert all(v >= -1e-6 for v in ex.get_balances().values())

        # 5. risk/observability state stayed live
        assert system.bus.get("risk_metrics")["n_assets"] == 2
        assert len(system.bus.get("portfolio_value_history")) == 500  # bounded
        assert (tmp_path / "dash.html").exists()

        # 5b. the device-runtime observatory survived the whole soak:
        #     SLO windows stayed bounded, the tick burn rate did not page
        #     in steady state, the per-device live-memory watermark is
        #     populated, and every carded donated program verified
        dp = system.devprof
        tick_q = dp.slos["tick"]
        assert tick_q.count >= TICKS and len(tick_q.buf) <= dp.window
        assert "LatencySLOBurnRateCritical" not in system.alerts.active
        assert dp.watermark.peak_bytes            # at least one device row
        assert dp.donation_failures == []
        for name, card in dp.cards.items():
            assert card.error is None, (name, card.error)
            if card.donation_ok is not None:
                assert card.donation_ok, f"{name} donation silently copied"
        text = system.metrics.exposition()
        assert 'crypto_trader_tpu_latency_p99_seconds{slo="tick"}' in text
        assert "crypto_trader_tpu_live_buffer_bytes_peak" in text

        # 6. the dashboard still renders every panel family at the end
        import urllib.request

        page = urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/").read().decode()
        for marker in ("Portfolio allocation", "social sentiment", "News",
                       "Asset correlation", "VaR 95% history",
                       "Model versions"):
            assert marker in page, f"missing panel: {marker}"
        health = urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/health").read().decode()
        assert '"healthy": true' in health
    finally:
        server.stop()
