"""SocialDataProvider: vectorized as-of joins vs pandas oracles.

Pins the TPU-native columnar join (social/provider.py) against the exact
pandas pipeline the reference runs per backtest
(`backtesting/data_manager.py:373-415` resample+ffill+merge_asof;
`backtesting/social_data_provider.py:44-199` point-in-time lookups and
derived indicators).
"""

import numpy as np
import pandas as pd
import pytest

from ai_crypto_trader_tpu.data.fetchers import SocialDaily
from ai_crypto_trader_tpu.data.ingest import load_social_csv, save_social_csv
from ai_crypto_trader_tpu.social.provider import (
    DEFAULT_METRICS,
    SocialDataProvider,
    asof_indices,
    resample_ffill,
)

DAY = 86_400


def make_daily(rng, days=12, start=1_700_000_000 - (1_700_000_000 % DAY)):
    ts = start + np.arange(days, dtype=np.int64) * DAY
    cols = {
        "social_volume": rng.integers(100, 50_000, days).astype(np.float32),
        "social_engagement": rng.integers(10, 5_000, days).astype(np.float32),
        "social_sentiment": rng.uniform(0.1, 0.9, days).astype(np.float32),
        "social_contributors": rng.integers(1, 500, days).astype(np.float32),
    }
    return SocialDaily(ts, cols)


@pytest.fixture()
def daily(rng):
    return make_daily(rng)


class TestAsofGolden:
    @pytest.mark.parametrize("interval,freq,step", [
        ("1m", "1min", 60), ("5m", "5min", 300),
        ("1h", "1h", 3600), ("1d", "1D", DAY),
    ])
    def test_matches_pandas_resample_merge_asof(self, daily, interval, freq, step):
        # candle grid: 3 days of candles starting mid-series, offset by 30s
        # so 'nearest' has to make real choices
        t0 = int(daily.timestamp[4]) + 30
        candle_ts = t0 + np.arange(0, 3 * DAY, step, dtype=np.int64)

        prov = SocialDataProvider(daily)
        ours = prov.metrics_at(candle_ts, interval)

        sdf = pd.DataFrame(
            {k: v for k, v in daily.columns.items()},
            index=pd.to_datetime(daily.timestamp, unit="s"),
        )
        sdf.index.name = "timestamp"
        resampled = sdf.resample(freq).ffill()
        mdf = pd.DataFrame({"timestamp": pd.to_datetime(candle_ts, unit="s")})
        merged = pd.merge_asof(mdf, resampled.reset_index(),
                               on="timestamp", direction="nearest")
        for name in daily.columns:
            np.testing.assert_allclose(
                ours[name], merged[name].to_numpy(np.float32),
                rtol=1e-6, err_msg=f"{name} @ {interval}")

    def test_columns_missing_get_defaults(self, daily):
        candle_ts = daily.timestamp[2] + np.arange(10) * 60
        ours = SocialDataProvider(daily).metrics_at(candle_ts)
        assert np.all(ours["twitter_volume"] == 0.0)
        assert np.all(ours["news_volume"] == 0.0)

    def test_before_series_start_nearest_takes_first_row(self, daily):
        # merge_asof direction='nearest' (data_manager.py:404-409) matches
        # pre-start candles to the FIRST social row — not defaults
        candle_ts = daily.timestamp[0] - DAY + np.arange(5) * 60
        ours = SocialDataProvider(daily).metrics_at(candle_ts)
        assert np.all(ours["social_volume"]
                      == daily.columns["social_volume"][0])

    def test_empty_series_is_default(self):
        empty = SocialDaily(np.zeros(0, np.int64))
        candle_ts = np.arange(5, dtype=np.int64) * 60
        ours = SocialDataProvider(empty).metrics_at(candle_ts)
        assert np.all(ours["social_sentiment"] == 0.5)
        assert np.all(ours["social_volume"] == 0.0)

    def test_asof_backward_matches_pandas(self, daily, rng):
        left = np.sort(rng.integers(daily.timestamp[0] - DAY,
                                    daily.timestamp[-1] + DAY, 200))
        idx = asof_indices(left, daily.timestamp, "backward")
        col = daily.columns["social_volume"]
        ldf = pd.DataFrame({"timestamp": pd.to_datetime(left, unit="s")})
        rdf = pd.DataFrame({
            "timestamp": pd.to_datetime(daily.timestamp, unit="s"),
            "v": col,
        })
        want = pd.merge_asof(ldf, rdf, on="timestamp",
                             direction="backward")["v"].to_numpy()
        got = np.where(idx >= 0, col[np.maximum(idx, 0)], np.nan)
        np.testing.assert_allclose(got, want, rtol=1e-6)

    def test_resample_ffill_grid(self):
        ts = np.asarray([0, DAY, 3 * DAY], np.int64)  # gap day 2
        grid, src = resample_ffill(ts, DAY)
        np.testing.assert_array_equal(grid, [0, DAY, 2 * DAY, 3 * DAY])
        np.testing.assert_array_equal(src, [0, 1, 1, 2])  # day 2 ffilled


class TestScalarParity:
    def test_point_lookup_is_most_recent_leq(self, daily):
        t = int(daily.timestamp[3]) + 7200  # 2h after day 3's stamp
        m = SocialDataProvider(daily).get_social_metrics_at(t)
        assert m["social_volume"] == float(daily.columns["social_volume"][3])

    def test_defaults_before_start(self, daily):
        m = SocialDataProvider(daily).get_social_metrics_at(
            int(daily.timestamp[0]) - 1)
        assert m == DEFAULT_METRICS

    def test_nan_falls_back_to_default(self, daily):
        daily.columns["social_sentiment"][5] = np.nan
        t = int(daily.timestamp[5]) + 60
        m = SocialDataProvider(daily).get_social_metrics_at(t)
        assert m["social_sentiment"] == 0.5

    def test_news_sentiment_prefers_news_column(self, daily):
        daily.columns["news_sentiment"] = np.full(len(daily), 0.8, np.float32)
        prov = SocialDataProvider(daily)
        t = int(daily.timestamp[-1]) + 60
        assert prov.get_news_sentiment(t)["sentiment"] == pytest.approx(0.8)

    def test_news_sentiment_falls_back_to_social(self, daily):
        prov = SocialDataProvider(daily)
        t = int(daily.timestamp[4]) + 60
        want = float(daily.columns["social_sentiment"][4])
        assert prov.get_news_sentiment(t)["sentiment"] == pytest.approx(want)


class TestIndicators:
    def reference_indicators(self, daily, t, intensity_window=30):
        """Direct port of social_data_provider.py:129-199."""
        mask = daily.timestamp <= t
        vol = daily.columns["social_volume"][mask].astype(np.float64)
        eng = daily.columns["social_engagement"][mask].astype(np.float64)
        if vol.size < 2:
            return {"social_momentum": 0.0, "social_trend": "neutral",
                    "social_intensity": 0.0, "social_engagement_rate": 0.0}
        momentum = (vol[-1] - vol[-2]) / max(vol[-2], 1.0) * 100.0
        trend = ("bullish" if momentum > 20 else
                 "bearish" if momentum < -20 else "neutral")
        pct = np.diff(vol[-intensity_window:]) / vol[-intensity_window:-1]
        intensity = pct.std(ddof=1) * 100.0 if pct.size > 1 else 0.0
        rate = eng[-1] / max(vol[-1], 1.0)
        return {"social_momentum": momentum, "social_trend": trend,
                "social_intensity": intensity, "social_engagement_rate": rate}

    def test_matches_reference_port(self, daily):
        prov = SocialDataProvider(daily)
        probes = [int(daily.timestamp[i]) + 3600 for i in (1, 4, 8, 11)]
        got = prov.indicators_at(np.asarray(probes, np.int64))
        for j, t in enumerate(probes):
            want = self.reference_indicators(daily, t)
            assert got["social_momentum"][j] == pytest.approx(
                want["social_momentum"], rel=1e-5)
            assert got["social_intensity"][j] == pytest.approx(
                want["social_intensity"], rel=1e-4)
            assert got["social_engagement_rate"][j] == pytest.approx(
                want["social_engagement_rate"], rel=1e-5)
            trend = {1.0: "bullish", -1.0: "bearish", 0.0: "neutral"}[
                float(got["social_trend"][j])]
            assert trend == want["social_trend"]

    def test_matches_reference_port_long_series(self, rng):
        # 60 days saturates the 30-day intensity window — catches
        # off-by-one errors in the trailing pct-change sample count
        daily = make_daily(rng, days=60)
        prov = SocialDataProvider(daily)
        probes = [int(daily.timestamp[i]) + 3600 for i in (35, 45, 59)]
        got = prov.indicators_at(np.asarray(probes, np.int64))
        for j, t in enumerate(probes):
            want = self.reference_indicators(daily, t)
            assert got["social_intensity"][j] == pytest.approx(
                want["social_intensity"], rel=1e-4)
            assert got["social_momentum"][j] == pytest.approx(
                want["social_momentum"], rel=1e-5)

    def test_cache_distinguishes_interior_gaps(self, daily):
        # same first/last/length, different interior grid: the cached
        # candle→daily index map must not be reused across them
        t0 = int(daily.timestamp[2])
        a = np.asarray([t0, t0 + 60, t0 + 3 * DAY], np.int64)
        b = np.asarray([t0, t0 + 2 * DAY, t0 + 3 * DAY], np.int64)
        prov = SocialDataProvider(daily)
        va = prov.metrics_at(a, "1m")["social_volume"]
        vb = prov.metrics_at(b, "1m")["social_volume"]
        assert vb[1] == daily.columns["social_volume"][4]  # day t0+2d
        assert va[1] == daily.columns["social_volume"][2]  # still day t0

    def test_fewer_than_two_points_zero(self, daily):
        prov = SocialDataProvider(daily)
        got = prov.indicators_at(np.asarray([int(daily.timestamp[0]) + 1]))
        assert got["social_momentum"][0] == 0.0
        assert got["social_engagement_rate"][0] == 0.0

    def test_market_update_enrichment(self, daily):
        prov = SocialDataProvider(daily)
        t = int(daily.timestamp[6]) + 60
        out = prov.generate_market_update_with_social(
            {"symbol": "BTCUSDC", "price": 50_000.0}, t)
        assert out["price"] == 50_000.0
        assert out["social_volume"] == float(daily.columns["social_volume"][6])
        assert out["social_trend"] in ("bullish", "bearish", "neutral")
        assert "social_momentum" in out and "news_sentiment" in out


class TestCsvRoundTrip:
    def test_save_load(self, daily, tmp_path):
        path = save_social_csv(daily, "BTCUSDC", str(tmp_path))
        back = load_social_csv(path)
        np.testing.assert_array_equal(back.timestamp, daily.timestamp)
        for k, v in daily.columns.items():
            np.testing.assert_allclose(back.columns[k], v, rtol=1e-6)


@pytest.mark.slow
class TestBacktestEndToEnd:
    def test_social_inputs_drive_population_backtest(self, daily, ohlcv):
        import jax

        from ai_crypto_trader_tpu.backtest import sample_params
        from ai_crypto_trader_tpu.backtest.evolvable import population_backtest

        d = {k: v for k, v in ohlcv.items() if k != "regime"}
        T = len(d["close"])
        candle_ts = int(daily.timestamp[2]) + np.arange(T, dtype=np.int64) * 60
        social = SocialDataProvider(daily).social_inputs(candle_ts, "1m")
        assert social.sentiment.shape == (T,)

        pop = sample_params(jax.random.PRNGKey(0), 8)
        with_s = population_backtest(d, pop, social=social)
        without = population_backtest(d, pop)
        assert np.all(np.isfinite(with_s.final_balance))
        # the social vote axis changes the vote denominator (5→6 indicator
        # groups, evolvable_signal), so the signal stream must differ
        assert (np.any(with_s.total_trades != without.total_trades)
                or np.any(with_s.final_balance != without.final_balance))
