"""Social strategy integrator + enhanced monitor reporting cadence.

Pins `services/social_strategy_integrator.py` (impact analysis, variant
dispatch, parameter tuning, service cadence) and the enhanced monitor's
periodic accuracy/lead-lag reports
(`enhanced_social_monitor_service.py:365-452`).
"""

import asyncio

import numpy as np
import pytest

from ai_crypto_trader_tpu.shell.bus import EventBus
from ai_crypto_trader_tpu.social import (
    SOCIAL_STRATEGY_TEMPLATES,
    SocialMonitorService,
    SocialStrategyIntegrator,
    analyze_social_impact,
    generate_social_strategy,
)


class Clock:
    def __init__(self):
        self.t = 1_000_000.0

    def __call__(self):
        return self.t


def correlated_series(rng, n=200, sign=1.0, lead=0):
    """Sentiment that (anti-)predicts the next-candle return, optionally
    leading by `lead` steps."""
    sent = rng.uniform(-1, 1, n)
    rets = np.zeros(n)
    for t in range(n - 1):
        src = sent[t - lead] if t - lead >= 0 else 0.0
        rets[t + 1] = sign * 0.01 * src + rng.normal(0, 0.001)
    close = 100 * np.cumprod(1 + rets)
    return sent, close


class TestImpactAnalysis:
    def test_positive_correlation_detected(self, rng):
        sent, close = correlated_series(rng, sign=1.0)
        imp = analyze_social_impact(sent, close)
        assert imp["correlations"]["1h"] > 0.3
        assert imp["data_points"] == 200
        assert "positive" in imp["returns_by_sentiment"] \
            or "very_positive" in imp["returns_by_sentiment"]

    def test_negative_correlation_detected(self, rng):
        sent, close = correlated_series(rng, sign=-1.0)
        imp = analyze_social_impact(sent, close)
        assert imp["correlations"]["1h"] < -0.3

    def test_all_buckets_partition(self, rng):
        sent, close = correlated_series(rng)
        imp = analyze_social_impact(sent, close)
        total = sum(v["count"] for v in imp["returns_by_sentiment"].values())
        assert total == len(sent)      # no sentiment value left unbucketed

    def test_insufficient_data(self):
        imp = analyze_social_impact(np.zeros(5), np.ones(5))
        assert imp["error"] == "insufficient_data"


class TestStrategyGeneration:
    def test_positive_corr_dispatches_trend_following(self, rng):
        sent, close = correlated_series(rng, sign=1.0)
        strat = generate_social_strategy("BTCUSDC",
                                         analyze_social_impact(sent, close))
        assert strat["strategy_type"] == "trend_following"
        # strong correlation raises the entry weight above the template
        assert strat["parameters"]["entry_weight"] > \
            SOCIAL_STRATEGY_TEMPLATES["trend_following"]["parameters"]["entry_weight"] - 0.2

    def test_negative_corr_dispatches_contrarian(self, rng):
        sent, close = correlated_series(rng, sign=-1.0)
        imp = analyze_social_impact(sent, close)
        if abs(imp["correlations"]["24h"]) <= 0.4:
            imp["correlations"]["24h"] = -0.5      # pin the dispatch input
        imp["optimal_lag"] = 0
        strat = generate_social_strategy("BTCUSDC", imp)
        assert strat["strategy_type"] == "contrarian"

    def test_leading_sentiment_dispatches_news_reactive(self, rng):
        sent, close = correlated_series(rng, sign=1.0)
        imp = analyze_social_impact(sent, close)
        imp["optimal_lag"], imp["optimal_lag_correlation"] = 6, 0.5
        strat = generate_social_strategy("BTCUSDC", imp)
        assert strat["strategy_type"] == "news_reactive"
        assert strat["parameters"]["sentiment_lookback"] == 12   # 2×lag

    def test_weak_correlation_damps_weights(self):
        imp = {"correlations": {"1h": 0.05, "4h": 0.05, "24h": 0.05},
               "strongest_timeframe": {"timeframe": "1h", "correlation": 0.05},
               "returns_by_sentiment": {}, "optimal_lag": 0,
               "optimal_lag_correlation": 0.0,
               "lead_lag_relationship": "coincident", "data_points": 100}
        strat = generate_social_strategy("X", imp)
        assert strat["parameters"]["entry_weight"] == 0.3
        assert strat["parameters"]["exit_weight"] == 0.2

    def test_error_propagates(self):
        assert "error" in generate_social_strategy(
            "X", {"error": "insufficient_data"})


def hourly_history(rng, n, t0=1_000_000):
    """Timestamped [ts, sentiment] pairs at hourly cadence."""
    return [[t0 + i * 3600, float(v)] for i, v in
            enumerate(rng.uniform(0, 1, n))]


def make_klines(n, rng):
    close = 100 * np.cumprod(1 + rng.normal(0, 0.003, n))
    return [[i, close[i], close[i] * 1.001, close[i] * 0.999, close[i],
             1000.0] for i in range(n)]


class TestIntegratorService:
    def test_generates_and_caches(self, rng):
        bus = EventBus()
        clock = Clock()
        bus.set("social_history_BTCUSDC", hourly_history(rng, 120))
        bus.set("historical_data_BTCUSDC_1h", make_klines(120, rng))
        svc = SocialStrategyIntegrator(bus, ["BTCUSDC"], now_fn=clock)
        out = asyncio.run(svc.run_once())
        assert out["generated"] == 1
        strat = bus.get("social_strategy_BTCUSDC")
        assert strat["strategy_type"] in SOCIAL_STRATEGY_TEMPLATES
        assert bus.get("social_impact_analysis_BTCUSDC")["data_points"] > 0
        # fresh strategy + check interval → no regeneration
        clock.t += 3601
        out = asyncio.run(svc.run_once())
        assert out["generated"] == 0
        # stale strategy regenerates
        clock.t += 6 * 3600
        out = asyncio.run(svc.run_once())
        assert out["generated"] == 1

    def test_no_data_no_strategy(self):
        bus = EventBus()
        svc = SocialStrategyIntegrator(bus, ["X"], now_fn=Clock())
        assert asyncio.run(svc.run_once())["generated"] == 0

    def test_no_data_does_not_burn_check_slot(self, rng):
        bus = EventBus()
        clock = Clock()
        svc = SocialStrategyIntegrator(bus, ["BTCUSDC"], now_fn=clock)
        assert asyncio.run(svc.run_once())["generated"] == 0
        # data arrives seconds later: the next tick generates immediately
        # instead of waiting out check_interval_s
        bus.set("social_history_BTCUSDC", hourly_history(rng, 120))
        bus.set("historical_data_BTCUSDC_1h", make_klines(120, rng))
        clock.t += 1
        assert asyncio.run(svc.run_once())["generated"] == 1

    def test_1m_fallback_resamples_to_hourly(self, rng):
        bus = EventBus()
        bus.set("social_history_BTCUSDC", hourly_history(rng, 50))
        bus.set("historical_data_BTCUSDC_1m", make_klines(600, rng))
        svc = SocialStrategyIntegrator(bus, ["BTCUSDC"], now_fn=Clock())
        sent, close = svc._series("BTCUSDC")
        assert len(close) == 10       # 600 minutes → 10 hourly closes
        # most recent candle is retained
        assert close[-1] == bus.get("historical_data_BTCUSDC_1m")[-1][4]


class TestEnhancedMonitorReports:
    def _service(self, rng, clock):
        bus = EventBus()
        bus.set("historical_data_BTCUSDC_1m", make_klines(300, rng))
        svc = SocialMonitorService(bus, ["BTCUSDC"], now_fn=clock,
                                   cache_ttl_s=0.0)
        return bus, svc

    def _accumulate(self, bus, svc, clock, rng, n=30):
        """The deterministic provider derives sentiment from
        market_data_{symbol}; vary it so sentiment leaves the neutral band."""
        for _ in range(n):
            bus.set("market_data_BTCUSDC",
                    {"price_change_15m": float(rng.normal(0, 3))})
            asyncio.run(svc.poll(force=True))
            clock.t += 300

    def test_reports_published_after_history(self, rng):
        clock = Clock()
        bus, svc = self._service(rng, clock)
        self._accumulate(bus, svc, clock, rng)
        out = asyncio.run(svc.run_once())
        assert out["accuracy"] and out["lead_lag"]
        rep = bus.get("social_accuracy_report")
        assert rep["total_symbols"] == 1
        assert 0.0 <= rep["average_direction_accuracy"] <= 1.0
        assert "BTCUSDC" in bus.get("social_lead_lag_report")["symbols"]
        assert bus.get("social_history_BTCUSDC")    # integrator feed exists

    def test_report_slot_not_burned_without_history(self, rng):
        clock = Clock()
        bus, svc = self._service(rng, clock)
        out = asyncio.run(svc.run_once())      # no history yet
        assert not out["accuracy"]
        # history arrives; the very next cycle reports without waiting a
        # full accuracy interval
        self._accumulate(bus, svc, clock, rng)
        assert asyncio.run(svc.run_once())["accuracy"]
