"""shell/stack.py adapters: EvolverService cadence/seeding, RegimeCadence
gating, and full-roster assembly (fast tier — the evolver is stubbed; the
real end-to-end run is tests/test_soak.py)."""

import asyncio

import numpy as np

from ai_crypto_trader_tpu.shell.bus import EventBus
from ai_crypto_trader_tpu.shell.stack import EvolverService, RegimeCadence


def _klines(n=300, base=100.0):
    return [[i * 60_000.0, base, base + 1, base - 1, base + 0.5, 10.0]
            for i in range(n)]


class StubEvolver:
    def __init__(self):
        self.calls = []

    async def evolve(self, ohlcv, current=None, metrics=None,
                     regime="ranging", history_length=0):
        self.calls.append({"n": len(ohlcv["close"]), "current": current,
                           "metrics": metrics, "regime": regime})
        return {"evolved": True, "method": "stub", "version": "v1"}


class TestEvolverService:
    def test_cadence_history_gate_and_partial_bar(self):
        bus = EventBus()
        stub = StubEvolver()
        clock = {"t": 0.0}
        svc = EvolverService(bus, stub, interval_s=600.0, min_candles=128,
                             now_fn=lambda: clock["t"])
        # no history yet → gated, interval slot NOT consumed
        assert asyncio.run(svc.run_once())["ran"] is False
        bus.set("historical_data_BTCUSDC_1m", _klines(256))
        out = asyncio.run(svc.run_once())
        assert out["ran"] and out["evolved"]
        # the venue's in-progress LAST bar is excluded from fitness data
        assert stub.calls[0]["n"] == 255
        # interval gate holds until interval_s elapses
        assert asyncio.run(svc.run_once())["ran"] is False
        clock["t"] = 600.0
        assert asyncio.run(svc.run_once())["ran"] is True

    def test_seeds_from_hot_swapped_params_and_regime(self):
        bus = EventBus()
        stub = StubEvolver()
        svc = EvolverService(bus, stub, interval_s=1.0, min_candles=64,
                             now_fn=lambda: 0.0)
        bus.set("historical_data_BTCUSDC_1m", _klines(256))
        bus.set("strategy_params", {"stop_loss": 4.5, "take_profit": 9.0,
                                    "bogus_key": 1.0})
        bus.set("market_regime_BTCUSDC", {"regime": "volatile"})
        asyncio.run(svc.run_once())
        call = stub.calls[0]
        # successive evolutions compound: current params come from the
        # hot-swap surface, unknown keys ignored, clamped to ranges
        assert float(call["current"].stop_loss) == 4.5
        assert float(call["current"].take_profit) == 9.0
        assert call["regime"] == "volatile"


class TestRegimeCadence:
    def test_per_symbol_interval_gating(self):
        class StubRegime:
            def __init__(self):
                self.updates = []

            async def update(self, symbol):
                self.updates.append(symbol)

        clock = {"t": 0.0}
        stub = StubRegime()
        cad = RegimeCadence(stub, ["A", "B"], interval_s=300.0,
                            now_fn=lambda: clock["t"])
        assert asyncio.run(cad.run_once())["updated"] == 2
        assert asyncio.run(cad.run_once())["updated"] == 0   # gated
        clock["t"] = 300.0
        assert asyncio.run(cad.run_once())["updated"] == 2
        assert stub.updates == ["A", "B", "A", "B"]


def test_build_full_stack_registers_roster():
    import sys

    sys.path.insert(0, "tests")
    from test_shell import _series

    from ai_crypto_trader_tpu.shell.exchange import FakeExchange
    from ai_crypto_trader_tpu.shell.launcher import TradingSystem
    from ai_crypto_trader_tpu.shell.stack import build_full_stack

    ex = FakeExchange({"BTCUSDC": _series()})
    system = TradingSystem(ex, ["BTCUSDC"], now_fn=lambda: 0.0)
    services = build_full_stack(
        system, grid_symbol="BTCUSDC", dca_symbol="BTCUSDC",
        # fast tier: skip the startup pattern training — the untrained
        # fallback path is itself under test (signals must carry the tag)
        cadences={"patterns": {"checkpoint": None, "train_on_start": False}})
    names = [s.name for s in services]
    assert names == ["social", "news", "patterns", "regime", "nn",
                     "evolver", "generator", "grid", "dca"]
    assert system.extra_services == services
    patterns = services[names.index("patterns")]
    assert patterns.recognizer.trained is False
