"""Strategy layer: evaluation metrics, CV, comparison, selection scoring,
evolution dispatch + hot swap, registry lifecycle, explainability,
grid / DCA / arbitrage."""

import asyncio

import numpy as np
import jax.numpy as jnp
import pytest

from ai_crypto_trader_tpu.backtest import default_params
from ai_crypto_trader_tpu.config import EvolutionParams, GAParams
from ai_crypto_trader_tpu.data.synthetic import generate_ohlcv
from ai_crypto_trader_tpu.shell.bus import EventBus
from ai_crypto_trader_tpu.strategy import (
    DCAStrategy,
    GridTrader,
    ModelRegistry,
    StrategyEvolver,
    StrategySelector,
    compare_strategies,
    cross_validate,
    explain_signal,
    find_triangle_arbitrage,
    trade_metrics,
)


def _arrays(n=1024, seed=7):
    d = generate_ohlcv(n=n, seed=seed)
    return {k: jnp.asarray(v) for k, v in d.items() if k != "regime"}


class TestTradeMetrics:
    TRADES = [{"pnl": p, "symbol": "BTCUSDC"} for p in
              [50, -20, 30, -10, 40, -20, 25, 60, -15, 10]]

    def test_suite_values(self):
        m = trade_metrics(self.TRADES, initial_balance=1000.0)
        assert m["total_trades"] == 10
        assert m["winning_trades"] == 6
        np.testing.assert_allclose(m["win_rate"], 60.0)
        np.testing.assert_allclose(m["profit_factor"], 215 / 65, rtol=1e-6)
        np.testing.assert_allclose(m["total_pnl"], 150.0)
        assert m["max_win_streak"] == 2
        assert m["max_loss_streak"] == 1
        assert m["sharpe_ratio"] > 0
        assert m["symbol_pnl"]["BTCUSDC"] == 150.0

    def test_empty(self):
        m = trade_metrics([])
        assert m["total_trades"] == 0 and m["sharpe_ratio"] == 0.0


@pytest.mark.slow
class TestCVAndComparison:
    def test_cross_validate(self):
        out = cross_validate(_arrays(), default_params(), k=3)
        assert len(out["folds"]) == 3
        assert set(f["regime"] for f in out["folds"]) <= {
            "bull", "bear", "ranging", "volatile"}
        assert np.isfinite(out["mean_sharpe"])

    def test_compare(self):
        import jax
        from ai_crypto_trader_tpu.backtest import sample_params
        p = sample_params(jax.random.PRNGKey(0), 3)
        named = {f"s{i}": jax.tree.map(lambda x: x[i], p) for i in range(3)}
        out = compare_strategies(_arrays(n=512), named)
        assert len(out["table"]) == 3
        assert out["best"] == out["ranked"][0]
        best, worst = out["ranked"][0], out["ranked"][-1]
        assert (out["table"][best]["sharpe_ratio"]
                >= out["table"][worst]["sharpe_ratio"])


class TestSelector:
    def test_regime_preference(self):
        sel = StrategySelector()
        strategies = [
            {"worker_id": "trend", "archetype": "trend_following",
             "metrics": {"sharpe_ratio": 1.0, "max_drawdown_pct": 5}},
            {"worker_id": "grid", "archetype": "grid",
             "metrics": {"sharpe_ratio": 1.0, "max_drawdown_pct": 5}},
        ]
        bull = sel.select(strategies, regime="bull")
        rang = sel.select(strategies, regime="ranging")
        assert bull["worker_id"] == "trend"
        assert rang["worker_id"] == "grid"

    def test_cooldown_blocks_switch(self):
        clock = [0.0]
        sel = StrategySelector(switch_cooldown_s=100, now_fn=lambda: clock[0])
        sel.record_switch("a")
        assert not sel.should_switch(0.5, 0.9)
        clock[0] += 101
        assert sel.should_switch(0.5, 0.9)
        assert not sel.should_switch(0.5, 0.55)  # below min edge


class TestEvolver:
    def test_needs_improvement_thresholds(self):
        ev = StrategyEvolver(EventBus(), cfg=EvolutionParams())
        assert ev.needs_improvement({"sharpe_ratio": 0.5, "win_rate": 60,
                                     "profit_factor": 2, "max_drawdown_pct": 5})
        assert not ev.needs_improvement({"sharpe_ratio": 2.0, "win_rate": 60,
                                         "profit_factor": 2.0,
                                         "max_drawdown_pct": 5})

    def test_dispatch(self):
        ev = StrategyEvolver(EventBus())
        assert ev.pick_method("volatile", 0) == "rl"
        assert ev.pick_method("bull", 50) == "ga"
        assert ev.pick_method("ranging", 0) == "llm"
        assert ev.pick_method("bear", 0) == "ga"

    def test_evolve_llm_path_and_hot_swap(self):
        async def go():
            bus = EventBus()
            reg = ModelRegistry()
            ev = StrategyEvolver(bus, registry=reg)
            q = bus.subscribe("strategy_update")
            out = await ev.evolve(_arrays(n=256), regime="ranging",
                                  metrics={"sharpe_ratio": 0.0, "win_rate": 0,
                                           "profit_factor": 0,
                                           "max_drawdown_pct": 50})
            assert out["evolved"] and out["method"] == "llm"
            assert bus.get("strategy_params") is not None
            env = q.get_nowait()
            assert "params" in env["data"]
            assert out["version"] in reg.entries
        asyncio.run(go())

    @pytest.mark.slow
    def test_evolve_ga_path(self):
        async def go():
            bus = EventBus()
            cfg = EvolutionParams(ga=GAParams(population_size=4, generations=1))
            ev = StrategyEvolver(bus, cfg=cfg)
            out = await ev.evolve(_arrays(n=256), regime="bull",
                                  history_length=30)
            assert out["evolved"] and out["method"] == "ga"
        asyncio.run(go())

    def test_regime_adjustment_clamped(self):
        from ai_crypto_trader_tpu.strategy.evolution import adjust_for_regime
        from ai_crypto_trader_tpu.backtest.strategy import PARAM_RANGES
        p = adjust_for_regime(default_params(), "volatile")
        for name, (lo, hi, _) in PARAM_RANGES.items():
            v = float(getattr(p, name))
            assert lo - 1e-6 <= v <= hi + 1e-6, name


class TestRegistry:
    def test_lifecycle_and_dedup(self, tmp_path):
        reg = ModelRegistry(path=str(tmp_path / "reg.json"))
        v1 = reg.register("strategy_params", {"a": 1.0, "b": 2.0})
        v_dup = reg.register("strategy_params", {"a": 1.0001, "b": 2.0001})
        assert v_dup == v1                     # near-duplicate suppressed
        v2 = reg.register("strategy_params", {"a": -5.0, "b": 9.0})
        assert v2 != v1
        reg.update_performance(v1, {"sharpe_ratio": 1.0})
        reg.update_performance(v2, {"sharpe_ratio": 2.0})
        assert reg.best("strategy_params")["version"] == v2
        reg.set_status(v2, "retired")
        assert reg.best("strategy_params")["version"] == v1
        cmp = reg.compare([v1, v2])
        assert cmp["best"] == v2
        # persistence round-trip
        reg2 = ModelRegistry(path=str(tmp_path / "reg.json"))
        assert v1 in reg2.entries


class TestExplain:
    def test_structure_and_artifact(self, tmp_path):
        out = explain_signal({"symbol": "BTCUSDC", "decision": "BUY",
                              "rsi": 28.0, "stoch_k": 15.0, "macd": 0.5,
                              "avg_volume": 2e5, "trend": "uptrend",
                              "trend_strength": 12.0, "confidence": 0.8},
                             out_dir=str(tmp_path))
        assert "rsi" in out["supporting_factors"]
        assert "stochastic" in out["supporting_factors"]
        assert sum(f["weight"] for f in out["factors"].values()) == 1.0
        assert "BUY" in out["narrative"]
        import os
        assert os.path.exists(out["artifact"])


class TestGrid:
    def test_levels(self):
        from ai_crypto_trader_tpu.strategy.grid import generate_grid_levels
        ar = generate_grid_levels(100, 200, 10, "arithmetic")
        assert len(ar) == 11
        np.testing.assert_allclose(np.diff(ar), 10.0)
        geo = generate_grid_levels(100, 400, 4, "geometric")
        np.testing.assert_allclose(geo[1] / geo[0], geo[2] / geo[1], rtol=1e-9)

    def test_round_trip_profit(self):
        g = GridTrader(lower=90, upper=110, n_grids=10, order_size=100,
                       fee_rate=0.0)
        out1 = g.step_simulation(high=100.0, low=94.9)   # fills buys ≤ 100
        assert out1["buys"] >= 2
        out2 = g.step_simulation(high=105.0, low=99.0)   # sells levels below 105
        assert out2["sells"] >= 1 and out2["pnl"] > 0
        assert g.realized_pnl > 0

    def test_oscillating_market_harvests(self):
        t = np.linspace(0, 8 * np.pi, 500)
        mid = 100 + 8 * np.sin(t)
        g = GridTrader(lower=88, upper=112, n_grids=12, fee_rate=0.0005)
        out = g.run_simulation(mid + 0.5, mid - 0.5)
        assert out["round_trips"] > 5
        assert out["realized_pnl"] > 0

    def test_regime_adaptive_counts(self):
        close = np.linspace(95, 105, 600)
        g = GridTrader.for_regime(close, "ranging")
        assert g.n_grids == 14
        g2 = GridTrader.for_regime(close, "volatile")
        assert g2.n_grids == 6


class TestDCA:
    def test_scheduling_and_dip_boost(self):
        from ai_crypto_trader_tpu.shell.exchange import FakeExchange
        from tests.test_shell import _series
        ex = FakeExchange({"BTCUSDC": _series()}, quote_balance=100_000)
        dca = DCAStrategy(base_amount=100, interval_s=3600)
        r1 = dca.maybe_purchase(ex, now=0.0)
        assert r1 is not None
        assert dca.maybe_purchase(ex, now=100.0) is None       # within interval
        r2 = dca.maybe_purchase(ex, now=3601.0)
        assert r2 is not None
        assert dca.average_cost() > 0

    def test_dip_multiplier(self):
        dca = DCAStrategy(base_amount=100, dip_threshold_pct=5, dip_multiplier=2)
        normal = dca.purchase_amount(price=100, recent_high=102)
        dip = dca.purchase_amount(price=94, recent_high=100)
        assert normal == 100 and dip == 200

    def test_value_averaging(self):
        dca = DCAStrategy(schedule="value_averaging", target_value_growth=100)
        assert dca.purchase_amount(100, 100, holdings_value=0) == 100
        dca.purchases.append({"price": 100, "quantity": 1, "amount": 100, "t": 0})
        # period 2 target 200, holdings now worth 150 → buy 50
        assert dca.purchase_amount(150, 150, holdings_value=150) == 50

    def test_rebalance(self):
        orders = DCAStrategy.rebalance_orders(
            holdings={"BTC": 1.0, "ETH": 0.0},
            prices={"BTC": 100.0, "ETH": 10.0},
            targets={"BTC": 0.5, "ETH": 0.5})
        sides = {o["symbol"]: o["side"] for o in orders}
        assert sides == {"BTCUSDC": "SELL", "ETHUSDC": "BUY"}


class TestArbitrage:
    def test_finds_planted_cycle(self):
        # USDC→BTC→ETH→USDC with a 1% planted edge
        tickers = {
            "BTCUSDC": {"bid": 100.0, "ask": 100.0},
            "ETHUSDC": {"bid": 10.1, "ask": 10.1},
            "ETHBTC": {"bid": 0.1, "ask": 0.1},
        }
        out = find_triangle_arbitrage(tickers, ["USDC", "BTC", "ETH"],
                                      fee_rate=0.0, min_profit_pct=0.1)
        assert out, "planted arbitrage must be found"
        assert out[0]["profit_pct"] == pytest.approx(1.0, rel=1e-3)

    def test_fees_kill_marginal_cycle(self):
        tickers = {
            "BTCUSDC": {"bid": 100.0, "ask": 100.0},
            "ETHUSDC": {"bid": 10.02, "ask": 10.02},
            "ETHBTC": {"bid": 0.1, "ask": 0.1},
        }
        out = find_triangle_arbitrage(tickers, ["USDC", "BTC", "ETH"],
                                      fee_rate=0.001, min_profit_pct=0.0)
        assert not out                        # 0.2% gross < 0.3% fees

    def test_executable_volume(self):
        from ai_crypto_trader_tpu.strategy.arbitrage import executable_volume
        books = [{"asks": [[100, 5]], "bids": []},
                 {"asks": [], "bids": [[10, 20]]},
                 {"asks": [[0.1, 1000]], "bids": []}]
        v = executable_volume(books, ["BUY", "SELL", "BUY"])
        assert v == pytest.approx(100.0)      # binding leg: 0.1 × 1000
