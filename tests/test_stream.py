"""Streaming-native ingest: recorded miniTicker AND kline frames drive the
monitor's refresh path — throttle/filter/batch semantics from the
reference (`services/market_monitor_service.py:374-403,615`;
`auto_trader.py:33-123`) plus the supervised feed lifecycle: continuity
enforcement (duplicate/out-of-order/gap handling vs the poll-path
oracle), bounded REST backfill, reconnect supervision, degrade-to-poll,
and the stream chaos soak.  Zero egress — every frame is injected."""

import asyncio
import json
import os
import random

import numpy as np
import pytest

from ai_crypto_trader_tpu.data.ingest import OHLCV
from ai_crypto_trader_tpu.data.synthetic import generate_ohlcv
from ai_crypto_trader_tpu.shell.bus import EventBus
from ai_crypto_trader_tpu.shell.exchange import FakeExchange
from ai_crypto_trader_tpu.shell.monitor import MarketMonitor
from ai_crypto_trader_tpu.shell.stream import (
    BinanceStreamSource,
    DepthCapture,
    MarketStream,
    StreamSupervisor,
    binance_kline_url,
    depth_frame,
    kline_frame,
    replay_frames,
)
from ai_crypto_trader_tpu.testing.chaos import CountingKlines

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _series(n=600, seed=5, symbol="BTCUSDC"):
    d = generate_ohlcv(n=n, seed=seed)
    return OHLCV(timestamp=np.arange(n, dtype=np.int64) * 60_000,
                 open=d["open"], high=d["high"], low=d["low"],
                 close=d["close"], volume=d["volume"] * 1000, symbol=symbol)


class Clock:
    def __init__(self):
        self.t = 1_000_000.0

    def __call__(self):
        return self.t


def _frame(*tickers):
    return json.dumps([
        {"e": "24hrMiniTicker", "s": s, "c": str(c), "q": str(q)}
        for (s, c, q) in tickers
    ])


def _setup(symbols=("BTCUSDC", "ETHUSDC")):
    clock = Clock()
    bus = EventBus(now_fn=clock)
    series = {s: _series(seed=10 + i, symbol=s)
              for i, s in enumerate(symbols)}
    ex = FakeExchange(series, quote_balance=10_000)
    ex.advance(steps=600)
    mon = MarketMonitor(bus, ex, symbols=list(symbols), now_fn=clock,
                        kline_limit=128)
    return clock, bus, mon


class TestIngest:
    def test_frame_marks_symbols_and_sets_tickers(self):
        clock, bus, mon = _setup()
        st = MarketStream(mon, now_fn=clock)
        marked = st.ingest_frame(_frame(("BTCUSDC", 50_000, 1e6),
                                        ("ETHUSDC", 3_000, 5e5)))
        assert marked == ["BTCUSDC", "ETHUSDC"]
        assert bus.get("ticker_BTCUSDC")["price"] == 50_000.0
        assert bus.get("ticker_ETHUSDC")["quote_volume"] == 5e5

    @pytest.mark.slow
    def test_throttle_suppresses_hot_symbol(self):
        clock, bus, mon = _setup()
        st = MarketStream(mon, now_fn=clock, throttle_s=5.0)
        assert st.ingest_frame(_frame(("BTCUSDC", 50_000, 1e6)))
        asyncio.run(st.drain())                   # clear the pending set
        clock.t += 1.0
        assert st.ingest_frame(_frame(("BTCUSDC", 50_100, 1e6))) == []
        # the tick itself still lands (executor needs sub-candle prices)
        assert bus.get("ticker_BTCUSDC")["price"] == 50_100.0
        clock.t += 5.0
        assert st.ingest_frame(_frame(("BTCUSDC", 50_200, 1e6))) == \
            ["BTCUSDC"]

    def test_volume_filter(self):
        clock, bus, mon = _setup()
        st = MarketStream(mon, now_fn=clock, min_quote_volume=1e5)
        assert st.ingest_frame(_frame(("BTCUSDC", 50_000, 1e4))) == []
        assert bus.get("ticker_BTCUSDC") is None

    def test_unknown_symbol_ignored(self):
        clock, bus, mon = _setup()
        st = MarketStream(mon, now_fn=clock)
        assert st.ingest_frame(_frame(("DOGEUSDC", 0.1, 1e6))) == []

    def test_malformed_frames_dropped(self):
        clock, bus, mon = _setup()
        st = MarketStream(mon, now_fn=clock)
        assert st.ingest_frame("not json{") == []
        assert st.ingest_frame(json.dumps({"no": "data"})) == []
        assert st.ingest_frame(json.dumps([{"s": "BTCUSDC"}])) == []  # no c
        assert st.frames_in == 3

    def test_combined_stream_envelope(self):
        clock, bus, mon = _setup()
        st = MarketStream(mon, now_fn=clock)
        env = json.dumps({"stream": "!miniTicker@arr",
                          "data": json.loads(_frame(("BTCUSDC", 9e4, 1e6)))})
        assert st.ingest_frame(env) == ["BTCUSDC"]


class TestDrain:
    def test_drain_publishes_through_monitor(self):
        clock, bus, mon = _setup()
        st = MarketStream(mon, now_fn=clock)
        st.ingest_frame(_frame(("BTCUSDC", 50_000, 1e6)))
        n = asyncio.run(st.drain())
        assert n == 1
        upd = bus.get("market_data_BTCUSDC")
        assert upd is not None and upd["symbol"] == "BTCUSDC"
        assert bus.published_counts["market_updates"] == 1

    def test_batch_size_limits_one_drain(self):
        symbols = tuple(f"A{i:02d}USDC" for i in range(8))
        clock, bus, mon = _setup(symbols)
        st = MarketStream(mon, now_fn=clock, batch_size=5)
        st.ingest_frame(_frame(*[(s, 100.0, 1e6) for s in symbols]))
        assert asyncio.run(st.drain()) == 5       # first batch of 5 (:403)
        assert asyncio.run(st.drain()) == 3       # remainder
        assert asyncio.run(st.drain()) == 0


class TestRun:
    def test_replay_source_end_to_end(self):
        clock, bus, mon = _setup()
        st = MarketStream(mon, now_fn=clock)
        frames = [
            _frame(("BTCUSDC", 50_000, 1e6)),
            "garbage",
            _frame(("ETHUSDC", 3_000, 5e5), ("BTCUSDC", 50_050, 1e6)),
        ]
        published = asyncio.run(st.run(replay_frames(frames)))
        assert published == 2                     # BTC throttled on frame 3
        assert bus.get("market_data_BTCUSDC") is not None
        assert bus.get("market_data_ETHUSDC") is not None
        assert st.ticks_in == 3


class TestRealSourceGate:
    def test_binance_source_requires_ws_library(self):
        try:
            import websockets  # noqa: F401
            pytest.skip("websockets installed; gate not reachable")
        except ImportError:
            pass
        with pytest.raises(RuntimeError, match="websockets"):
            BinanceStreamSource()

    def test_binance_source_accepts_connection_params(self):
        """Satellite: url / ping-interval / connect-timeout are ctor
        parameters (the gate fires first here, but the signature must
        accept them — a live deployment tunes all three)."""
        try:
            import websockets  # noqa: F401
        except ImportError:
            with pytest.raises(RuntimeError, match="websockets"):
                BinanceStreamSource("wss://example/ws", ping_interval_s=5.0,
                                    connect_timeout_s=2.0)
            return
        src = BinanceStreamSource("wss://example/ws", ping_interval_s=5.0,
                                  connect_timeout_s=2.0)
        assert src.url == "wss://example/ws"
        assert src.ping_interval_s == 5.0 and src.connect_timeout_s == 2.0
        assert src._websockets is not None          # imported once, cached

    def test_combined_kline_url(self):
        url = binance_kline_url(["BTCUSDC", "ETHUSDC"], ["1m", "5m"])
        assert url.endswith("btcusdc@kline_1m/btcusdc@kline_5m/"
                            "ethusdc@kline_1m/ethusdc@kline_5m")
        assert url.startswith("wss://")


# ---------------------------------------------------------------------------
# kline-stream ingestion: frames → continuity-checked books → fused engine
# ---------------------------------------------------------------------------

def _kline_setup(symbols=("BTCUSDC", "ETHUSDC"), n=2400, limit=128,
                 advance=2200):
    """Rig where ALL FOUR frames reach a full window (the 15m frame needs
    15×limit 1m candles) so the zero-REST steady state is reachable."""
    clock = Clock()
    bus = EventBus(now_fn=clock)
    series = {s: _series(n=n, seed=10 + i, symbol=s)
              for i, s in enumerate(symbols)}
    ex = FakeExchange(series, quote_balance=10_000)
    ex.advance(steps=advance)
    counting = CountingKlines(ex)
    mon = MarketMonitor(bus, counting, symbols=list(symbols), now_fn=clock,
                        kline_limit=limit)
    return clock, bus, mon, ex, counting




def _venue_frames(ex, symbols, intervals, *, event_ms=None):
    from ai_crypto_trader_tpu.testing.chaos import kline_frames_for

    return kline_frames_for(ex, symbols, intervals, event_ms=event_ms)


class TestKlineIngest:
    def test_kline_frame_round_trip_and_ticker_times(self):
        clock, bus, mon, ex, _ = _kline_setup()
        st = MarketStream(mon, now_fn=clock)
        row = ex.get_klines("BTCUSDC", "1m", 2)[-1]
        ev_ms = int(clock.t * 1000) - 2500            # exchange 2.5 s behind
        marked = st.ingest_frame(kline_frame("BTCUSDC", "1m", row,
                                             closed=True, event_ms=ev_ms))
        assert marked == ["BTCUSDC"]
        tick = bus.get("ticker_BTCUSDC")
        assert tick["price"] == float(row[4])
        # satellite: BOTH exchange event time and host receive time ride
        # the ticker entry — the executor's staleness fence needs real data
        assert tick["event_time"] == pytest.approx(ev_ms / 1000.0)
        assert tick["recv_time"] == clock.t
        # the lane needs a seed before continuity can be enforced
        book = st._books[("BTCUSDC", "1m")]
        assert book.needs_backfill

    def test_combined_stream_kline_envelope(self):
        clock, bus, mon, ex, _ = _kline_setup()
        st = MarketStream(mon, now_fn=clock)
        row = ex.get_klines("BTCUSDC", "1m", 2)[-1]
        frame = kline_frame("BTCUSDC", "1m", row, combined=True)
        assert st.ingest_frame(frame) == ["BTCUSDC"]

    def test_malformed_kline_counted(self):
        clock, bus, mon, ex, _ = _kline_setup()
        st = MarketStream(mon, now_fn=clock)
        assert st.ingest_frame(json.dumps({"e": "kline", "s": "BTCUSDC",
                                           "k": {"i": "1m"}})) == []
        assert st.malformed_frames == 1

    def test_exotic_interval_units_parse(self):
        """Every real Binance kline unit has a continuity step — '1s',
        '1w', '1M' subscriptions must not KeyError the stage."""
        from ai_crypto_trader_tpu.shell.stream import interval_ms
        assert interval_ms("1s") == 1_000
        assert interval_ms("1w") == 7 * 86_400_000
        assert interval_ms("1M") == 30 * 86_400_000
        with pytest.raises(ValueError):
            interval_ms("7x")
        with pytest.raises(ValueError):
            interval_ms("")

    def test_unrecognized_interval_poisons_frame_not_stage(self):
        """A frame whose interval the step table can't parse is counted
        malformed and dropped — an escaped exception would quarantine
        EVERY lane, not just the bad one."""
        clock, bus, mon, ex, _ = _kline_setup()
        mon.intervals = ("1m", "7x")                 # operator typo
        st = MarketStream(mon, now_fn=clock)
        row = ex.get_klines("BTCUSDC", "1m", 2)[-1]
        bad = kline_frame("BTCUSDC", "7x", row, closed=True)
        assert st.ingest_frame(bad) == []            # no crash, no lane
        assert st.malformed_frames == 1
        assert ("BTCUSDC", "7x") not in st._books
        # the good lane keeps working
        good = kline_frame("BTCUSDC", "1m", row, closed=True)
        assert st.ingest_frame(good) == ["BTCUSDC"]

    def test_kline_per_candle_volume_not_filtered(self):
        """min_quote_volume is the miniTicker 24h-volume discovery filter;
        a kline frame's `q` is ONE candle's quote volume and must never be
        compared against it (it would reject virtually every frame)."""
        clock, bus, mon, ex, _ = _kline_setup()
        st = MarketStream(mon, now_fn=clock, min_quote_volume=1_000_000.0)
        row = ex.get_klines("BTCUSDC", "1m", 2)[-1]
        frame = kline_frame("BTCUSDC", "1m", row, closed=True,
                            quote_volume=700.0)      # ~1M/day per-candle
        assert st.ingest_frame(frame) == ["BTCUSDC"]
        assert bus.get("ticker_BTCUSDC") is not None

    def test_unfed_book_lane_never_freezes(self):
        """A lane the stream is not actually feeding (kline channel missing
        from the subscription) must keep REST-fetching fresh rows on every
        drain instead of serving its one-time seed forever."""
        clock, bus, mon, ex, counting = _kline_setup(symbols=("BTCUSDC",))
        st = MarketStream(mon, now_fn=clock)
        first = st.serve_klines("BTCUSDC", "1m")     # seed (REST)
        calls = counting.kline_calls
        clock.t += 300.0                             # lane stays silent
        ex.advance(steps=5)
        again = st.serve_klines("BTCUSDC", "1m")
        assert counting.kline_calls > calls          # re-fetched, not frozen
        assert again[-1][0] > first[-1][0]           # fresh rows served
        # ... while a live-fed lane serves its book with zero REST
        row = ex.get_klines("BTCUSDC", "1m", 2)[-1]
        st.ingest_frame(kline_frame("BTCUSDC", "1m", row, closed=True))
        calls = counting.kline_calls
        assert st.serve_klines("BTCUSDC", "1m")[-1][0] == row[0]
        assert counting.kline_calls == calls

    def test_off_interval_kline_updates_ticker_only(self):
        clock, bus, mon, ex, _ = _kline_setup()
        st = MarketStream(mon, now_fn=clock)
        row = ex.get_klines("BTCUSDC", "1m", 2)[-1]
        assert st.ingest_frame(kline_frame("BTCUSDC", "1h", row)) == []
        assert st.frames_ignored == 1
        assert bus.get("ticker_BTCUSDC") is not None
        assert ("BTCUSDC", "1h") not in st._books

    def test_continuity_dup_ooo_gap(self):
        clock, bus, mon, ex, _ = _kline_setup()
        st = MarketStream(mon, now_fn=clock)
        book = st._book("BTCUSDC", "1m")
        rows = ex.get_klines("BTCUSDC", "1m", 128)
        book.seed(rows)
        step = 60_000
        nxt = [rows[-1][0] + step, 1.0, 2.0, 0.5, 1.5, 10.0,
               0, 0.0, 0, 0.0, 0.0, 0]
        assert book.apply(nxt) == "append"
        assert book.apply(list(nxt)) == "dup"          # exact re-send
        old = list(rows[-3])
        assert book.apply(old) == "out_of_order"
        gap = list(nxt)
        gap[0] = nxt[0] + 3 * step                     # skipped 2 candles
        assert book.apply(gap) == "gap"
        assert book.needs_backfill
        # neither dup, ooo nor the gap row itself landed in the window
        assert book.rows[-1][0] == nxt[0]

    def test_lost_final_update_flags_backfill_not_torn_bar(self):
        """The tail bar's final (x=true) update was lost: appending the
        next candle would freeze the torn bar — the book demands a REST
        repair instead."""
        clock, bus, mon, ex, _ = _kline_setup()
        st = MarketStream(mon, now_fn=clock)
        book = st._book("BTCUSDC", "1m")
        book.seed(ex.get_klines("BTCUSDC", "1m", 128))
        t0 = book.rows[-1][0]
        bar1 = [t0 + 60_000, 1.0, 2.0, 0.5, 1.5, 10.0, 0, 0.0, 0, 0.0, 0.0, 0]
        assert book.apply(bar1, closed=False) == "append"  # in-progress
        # ... its final form never arrives; the NEXT bar shows up
        bar2 = [t0 + 120_000, 1.5, 2.5, 1.0, 2.0, 9.0, 0, 0.0, 0, 0.0, 0.0, 0]
        assert book.apply(bar2, closed=True) == "unconfirmed"
        assert book.needs_backfill
        # the confirmed path: final update lands, then the append is clean
        book.needs_backfill = False
        assert book.apply(list(bar1), closed=True) == "dup"  # flag rides dups
        assert book.apply(bar2, closed=True) == "append"

    def test_pending_is_ordered_set_and_last_seen_bounded(self):
        """Satellite: `_pending` dict-backed ordered set (O(1) membership),
        `_last_seen` LRU-bounded."""
        clock, bus, mon, ex, _ = _kline_setup()
        st = MarketStream(mon, now_fn=clock, restrict_to_universe=False,
                          max_tracked=8)
        frame = _frame(*[(f"Z{i:03d}USDC", 1.0, 1e6) for i in range(40)])
        marked = st.ingest_frame(frame)
        assert marked == [f"Z{i:03d}USDC" for i in range(40)]  # order kept
        assert list(st._pending) == marked
        assert len(st._last_seen) <= 8                 # bounded under churn
        # membership stays O(1)-correct: re-offering doesn't duplicate
        clock.t += 10.0
        st.ingest_frame(frame)
        assert list(st._pending) == marked


class TestStreamedDrains:
    def test_zero_rest_klines_on_happy_path(self):
        """Tentpole (a): after the one-time backfill seed, streamed drains
        publish with ZERO REST kline calls and ONE fused dispatch each."""
        clock, bus, mon, ex, counting = _kline_setup(symbols=("BTCUSDC",))
        st = MarketStream(mon, now_fn=clock)
        ivs = mon.intervals

        async def go():
            # seed drain: books empty → bounded REST backfill (counted)
            for f in _venue_frames(ex, ["BTCUSDC"], ivs,
                                   event_ms=int(clock.t * 1000)):
                st.ingest_frame(f)
            n = await st.drain()
            assert n == 1
            seed_calls = counting.kline_calls
            assert seed_calls >= len(ivs)              # the backfill seed
            eng = mon._engine
            # steady state: frames only, no REST
            for _ in range(5):
                ex.advance(steps=1)
                clock.t += 60.0
                for f in _venue_frames(ex, ["BTCUSDC"], ivs,
                                       event_ms=int(clock.t * 1000)):
                    st.ingest_frame(f)
                d0 = eng.dispatch_count
                n = await st.drain()
                assert n == 1
                assert eng.dispatch_count == d0 + 1    # ONE dispatch/drain
                assert not eng.last_stats["full_seed"]
            assert counting.kline_calls == seed_calls  # ZERO further REST
            assert st.streamed_rows > 0                # ingest_row fed ring
            # ring parity: engine window == the venue's own REST answer
            for iv in ivs:
                oracle = ex.get_klines("BTCUSDC", iv, mon.kline_limit)
                want = np.asarray([r[1:6] for r in oracle], np.float32)
                s, f = eng.sym_index["BTCUSDC"], eng.iv_index[iv]
                np.testing.assert_array_equal(eng._win[s, f], want)
                assert list(eng._ts[s, f]) == [r[0] for r in oracle]

        asyncio.run(go())

    def test_gap_triggers_bounded_backfill(self):
        """Tentpole (c): a reconnect window (missed candles) marks the lane
        and the next drain REST-backfills it BEFORE any ring upload — the
        window ends contiguous and equal to the oracle."""
        clock, bus, mon, ex, counting = _kline_setup(symbols=("BTCUSDC",))
        st = MarketStream(mon, now_fn=clock)
        ivs = mon.intervals

        async def go():
            for f in _venue_frames(ex, ["BTCUSDC"], ivs):
                st.ingest_frame(f)
            await st.drain()
            # a 5-candle outage the stream never saw
            ex.advance(steps=5)
            clock.t += 300.0
            gap_frames = _venue_frames(ex, ["BTCUSDC"], ["1m"])
            st.ingest_frame(gap_frames[0])
            assert st.gaps >= 1
            assert st._books[("BTCUSDC", "1m")].needs_backfill
            before = counting.kline_calls
            n = await st.drain()
            assert n == 1
            assert counting.kline_calls > before       # REST backfill ran
            book = st._books[("BTCUSDC", "1m")]
            oracle = ex.get_klines("BTCUSDC", "1m", mon.kline_limit)
            assert [r[0] for r in book.rows] == [r[0] for r in oracle]
            steps = np.diff([r[0] for r in book.rows])
            assert (steps == 60_000).all()             # contiguous again

        asyncio.run(go())

    def test_fault_injection_never_tears_ring_vs_poll_oracle(self):
        """Property test: duplicate / out-of-order / malformed / partial /
        stale frames NEVER change ring contents vs the poll-path oracle."""
        from ai_crypto_trader_tpu.testing.chaos import (
            ChaosFrameSource, FaultSchedule)

        clock, bus, mon, ex, counting = _kline_setup(symbols=("BTCUSDC",))
        st = MarketStream(mon, now_fn=clock)
        chaos = ChaosFrameSource(FaultSchedule(seed=13, rates={
            "fs_dup": 0.15, "fs_ooo": 0.15, "fs_malformed": 0.1,
            "fs_stale": 0.1}))
        ivs = mon.intervals

        async def go():
            for f in _venue_frames(ex, ["BTCUSDC"], ivs):
                st.ingest_frame(f)
            await st.drain()
            for _ in range(30):
                ex.advance(steps=1)
                clock.t += 60.0
                frames, _ = chaos.filter(_venue_frames(
                    ex, ["BTCUSDC"], ivs, event_ms=int(clock.t * 1000)))
                for f in frames:
                    st.ingest_frame(f)
                await st.drain()
            # the schedule actually injected several kinds
            kinds = {f for _, _, f in chaos.schedule.injected}
            assert len(kinds) >= 3, kinds
            assert st.dup_frames + st.ooo_frames + st.malformed_frames > 0
            # settle: two fault-free ticks so the CURRENT in-progress bar's
            # newest update lands (a lost in-progress update legitimately
            # leaves the unfinished bar one tick stale until the next
            # frame; closed candles are protected by the unconfirmed-tail
            # backfill and must match bit-for-bit regardless)
            chaos.schedule.rates = {}
            for _ in range(2):
                ex.advance(steps=1)
                clock.t += 60.0
                frames, _ = chaos.filter(_venue_frames(
                    ex, ["BTCUSDC"], ivs, event_ms=int(clock.t * 1000)))
                for f in frames:
                    st.ingest_frame(f)
                await st.drain()
            eng = mon._engine
            for iv in ivs:
                oracle = ex.get_klines("BTCUSDC", iv, mon.kline_limit)
                want = np.asarray([r[1:6] for r in oracle], np.float32)
                s, f = eng.sym_index["BTCUSDC"], eng.iv_index[iv]
                np.testing.assert_array_equal(eng._win[s, f], want)
                ts = eng._ts[s, f]
                assert (np.diff(ts) > 0).all()         # strictly ordered
                assert len(set(ts.tolist())) == len(ts)  # zero duplicates

        asyncio.run(go())


class TestStreamCurrentSkip:
    def test_steady_state_skips_full_ingest_ring_stays_oracle_equal(self):
        """Once a lane is warm and stream-fed, drains serve engine-current
        windows and the fused poll SKIPS the full-window re-diff — while
        the ring stays bit-equal to the venue oracle (the skip claims a
        zero-change diff; this pins that the claim is true)."""
        clock, bus, mon, ex, counting = _kline_setup(symbols=("BTCUSDC",))
        st = MarketStream(mon, now_fn=clock)
        ivs = mon.intervals

        async def go():
            for f in _venue_frames(ex, ["BTCUSDC"], ivs,
                                   event_ms=int(clock.t * 1000)):
                st.ingest_frame(f)
            assert await st.drain() == 1               # seed: full path
            eng = mon._engine
            ingests = {"n": 0}
            real_ingest = eng.ingest

            def counted(*a, **kw):
                ingests["n"] += 1
                return real_ingest(*a, **kw)

            eng.ingest = counted
            for _ in range(3):
                ex.advance(steps=1)
                clock.t += 60.0
                for f in _venue_frames(ex, ["BTCUSDC"], ivs,
                                       event_ms=int(clock.t * 1000)):
                    st.ingest_frame(f)
                assert await st.drain() == 1
            assert ingests["n"] == 0                   # every lane skipped
            assert st.served_current >= 3 * len(ivs)
            for iv in ivs:
                assert eng.lane_synced("BTCUSDC", iv)
                oracle = ex.get_klines("BTCUSDC", iv, mon.kline_limit)
                want = np.asarray([r[1:6] for r in oracle], np.float32)
                s, f = eng.sym_index["BTCUSDC"], eng.iv_index[iv]
                np.testing.assert_array_equal(eng._win[s, f], want)

        asyncio.run(go())

    def test_gap_takes_full_path_and_refused_row_clears_sync(self):
        """A reconnect gap must never be served engine-current: the book's
        needs_backfill forces the REST path (plain list, no provenance)
        and the repair drain takes the full-diff path; independently, a
        row the ENGINE refuses (its window lagging the book) drops the
        lane's synced flag at the engine layer."""
        clock, bus, mon, ex, counting = _kline_setup(symbols=("BTCUSDC",))
        st = MarketStream(mon, now_fn=clock)
        ivs = mon.intervals

        async def go():
            for f in _venue_frames(ex, ["BTCUSDC"], ivs,
                                   event_ms=int(clock.t * 1000)):
                st.ingest_frame(f)
            await st.drain()
            eng = mon._engine
            assert eng.lane_synced("BTCUSDC", "1m")
            # a 5-candle outage the stream never saw
            ex.advance(steps=5)
            clock.t += 300.0
            gap_row = ex.get_klines("BTCUSDC", "1m", 1)[-1]
            st.ingest_frame(_venue_frames(ex, ["BTCUSDC"], ["1m"])[0])
            assert st._books[("BTCUSDC", "1m")].needs_backfill
            served = st.serve_klines("BTCUSDC", "1m")   # REST path
            assert not getattr(served, "engine_current", False)
            # the engine layer's own guard: offering the ring a row that
            # doesn't extend its window contiguously refuses AND desyncs
            assert not eng.ingest_row("BTCUSDC", "1m", gap_row)
            assert not eng.lane_synced("BTCUSDC", "1m")
            assert await st.drain() == 1               # full-diff repair
            assert eng.lane_synced("BTCUSDC", "1m")
            oracle = ex.get_klines("BTCUSDC", "1m", mon.kline_limit)
            want = np.asarray([r[1:6] for r in oracle], np.float32)
            s, f = eng.sym_index["BTCUSDC"], eng.iv_index["1m"]
            np.testing.assert_array_equal(eng._win[s, f], want)

        asyncio.run(go())


# ---------------------------------------------------------------------------
# the supervised lifecycle
# ---------------------------------------------------------------------------

class TestSupervisor:
    def _sup(self, clock=None, **kw):
        clock = clock or Clock()
        bus = EventBus(now_fn=clock)
        series = {"BTCUSDC": _series(seed=3)}
        ex = FakeExchange(series)
        mon = MarketMonitor(bus, ex, symbols=["BTCUSDC"], now_fn=clock,
                            kline_limit=128, fused=False)
        st = MarketStream(mon, now_fn=clock)
        return clock, bus, StreamSupervisor(st, bus=bus, now_fn=clock, **kw)

    def test_bounded_queue_drops_oldest(self):
        clock, bus, sup = self._sup(queue_max=4)
        for i in range(10):
            sup.offer(f"frame{i}")
        assert len(sup._q) == 4
        assert list(sup._q) == ["frame6", "frame7", "frame8", "frame9"]
        assert sup.frames_dropped == 6                 # counted, not silent

    def test_disconnect_edges_and_flapping_alert(self):
        clock, bus, sup = self._sup(flap_threshold=3, flap_window_s=120.0)
        q = bus.subscribe("alerts")

        async def go():
            for _ in range(3):
                sup.offer("[]")
                sup.connection_lost("chaos")
                clock.t += 10.0
            sup.connection_lost("chaos again")         # no edge: already down
            await sup.step()

        asyncio.run(go())
        names = []
        while not q.empty():
            names.append(q.get_nowait()["data"]["name"])
        assert names.count("StreamDisconnected") == 3  # edge-triggered
        assert names.count("StreamFlapping") == 1
        assert sup.disconnects == 3 and sup.reconnects == 2

    def test_silence_watchdog_forces_disconnect(self):
        clock, bus, sup = self._sup(max_silence_s=30.0)
        sup.offer("[]")
        assert sup.connected
        clock.t += 45.0                                # silent past budget

        async def go():
            await sup.step()

        asyncio.run(go())
        assert not sup.connected
        assert sup.degraded()
        assert sup.disconnects == 1

    def test_degraded_before_first_frame_and_staleness(self):
        clock, bus, sup = self._sup(stale_after_s=30.0)
        assert sup.degraded()                          # never connected
        sup.offer("[]")
        assert not sup.degraded()
        clock.t += 31.0
        assert sup.degraded()                          # stale past budget
        assert sup.staleness() == pytest.approx(31.0)

    def test_pump_reconnects_with_backoff_and_jitter(self):
        clock, bus, sup = self._sup()
        sleeps = []

        async def fake_sleep(s):
            sleeps.append(s)

        async def dies_after(frames):
            for f in frames:
                yield f
            raise ConnectionError("socket reset")

        sources = [dies_after(['[{"s": "BTCUSDC", "c": "1", "q": "0"}]']),
                   dies_after(['[{"s": "BTCUSDC", "c": "2", "q": "0"}]'])]

        def factory():
            return sources.pop(0) if sources else None

        sup.source_factory = factory
        sup.sleep = fake_sleep
        asyncio.run(sup.pump())
        assert sup.frames_offered == 2
        assert sup.disconnects == 2                    # both sockets died
        assert sup.reconnects == 1                     # second connect
        assert len(sleeps) == 2 and all(s > 0 for s in sleeps)

    def test_pump_read_timeout_reconnects(self):
        clock, bus, sup = self._sup(connect_timeout_s=0.02,
                                    read_timeout_s=0.02)
        sleeps = []

        async def fake_sleep(s):
            sleeps.append(s)

        async def hangs():
            await asyncio.sleep(5)
            yield ""                                   # pragma: no cover

        sources = [hangs()]

        def factory():
            return sources.pop(0) if sources else None

        sup.source_factory = factory
        sup.sleep = fake_sleep
        asyncio.run(asyncio.wait_for(sup.pump(), 5))
        assert len(sleeps) == 1                        # backed off once

    def test_gauges_exported(self):
        from ai_crypto_trader_tpu.utils.metrics import MetricsRegistry

        clock, bus, sup = self._sup()
        sup.metrics = MetricsRegistry(now_fn=clock)
        sup.offer("not json")

        async def go():
            await sup.step()

        asyncio.run(go())
        text = sup.metrics.exposition()
        for name in ("stream_connected", "stream_staleness_seconds",
                     "stream_queue_depth", "stream_frames_total",
                     "stream_malformed_frames_total"):
            assert f"crypto_trader_tpu_{name}" in text, name


# ---------------------------------------------------------------------------
# depth-frame capture: ring + journal + telemetry (ISSUE 13)
# ---------------------------------------------------------------------------

def _depth_frames(symbol="BTCUSDC", n=6, combined=False, snapshot=False):
    frames = []
    for i in range(n):
        bids = [[100.0 - 0.1 * j, 5.0 + i + j] for j in range(4)]
        asks = [[100.1 + 0.1 * j, 4.0 + i + j] for j in range(4)]
        frames.append(depth_frame(symbol, bids, asks, event_ms=1000 + i,
                                  first_id=10 * i + 1, final_id=10 * (i + 1),
                                  snapshot=snapshot, combined=combined))
    return frames


class TestDepthCapture:
    def _stream(self, **capture_kw):
        clock, bus, mon = _setup()
        dc = DepthCapture(**capture_kw)
        return MarketStream(mon, now_fn=clock, depth=dc), dc

    def test_diff_and_snapshot_frames_round_trip(self):
        st, dc = self._stream()
        for f in _depth_frames(n=3):
            st.ingest_frame(f)
        st.ingest_frame(_depth_frames(n=1, snapshot=True,
                                      combined=True)[0])
        assert dc.frames_total == 4 and dc.malformed == 0
        recs = dc.records()
        assert recs[0]["kind"] == "diff" and recs[-1]["kind"] == "snapshot"
        assert recs[0]["bids"][0] == [100.0, 5.0]      # floats, not strings
        assert recs[0]["symbol"] == "BTCUSDC"
        # a snapshot payload has no symbol field — it is recovered from
        # the combined-stream channel name
        assert recs[-1]["symbol"] == "BTCUSDC"
        # contiguous diff ids (U == last u + 1): no gap counted
        assert dc.gaps == 0

    def test_symbol_filter_sees_enveloped_snapshots(self):
        st, dc = self._stream(symbols={"BTCUSDC"})
        st.ingest_frame(_depth_frames(n=1, snapshot=True, combined=True)[0])
        assert dc.frames_total == 1 and dc.frames_ignored == 0

    def test_update_id_gap_counted(self):
        st, dc = self._stream()
        frames = _depth_frames(n=4)
        st.ingest_frame(frames[0])
        st.ingest_frame(frames[2])                     # skipped frames[1]
        assert dc.gaps == 1

    def test_ring_bounded_drop_oldest_and_watermark(self):
        st, dc = self._stream(ring_max=4)
        for f in _depth_frames(n=7):
            st.ingest_frame(f)
        assert len(dc.records()) == 4
        assert dc.watermark == 1.0
        # aging out of a keep-last-N ring is RETENTION, not loss: the
        # drop counter (the alert input) stays untouched
        assert dc.frames_dropped == 0
        # the oldest three frames are gone, the newest four remain
        assert [r["E"] for r in dc.records()] == [1003, 1004, 1005, 1006]

    def test_journal_checksummed_jsonl(self, tmp_path):
        from ai_crypto_trader_tpu.utils.journal import replay

        path = str(tmp_path / "depth.jsonl")
        st, dc = self._stream(path=path)
        for f in _depth_frames(n=5):
            st.ingest_frame(f)
        dc.close()
        records, stats = replay(path)
        assert stats["replayed"] == 5 and stats["corrupt_records"] == 0
        assert all(r["kind"] == "depth" for r in records)
        assert records[0]["data"]["bids"][0] == [100.0, 5.0]

    def test_journal_bounded_and_exhaustion_counted(self, tmp_path):
        path = str(tmp_path / "depth.jsonl")
        st, dc = self._stream(path=path, journal_max=3)
        for f in _depth_frames(n=6):
            st.ingest_frame(f)
        assert dc.journaled == 3                       # disk stays bounded
        assert dc.frames_total == 6                    # ring keeps capturing
        assert dc.frames_dropped == 3                  # unpersisted frames
        assert dc.journal_exhausted is True
        # a ring-only capture never reports loss or exhaustion
        st2, dc2 = self._stream(ring_max=2)
        for f in _depth_frames(n=5):
            st2.ingest_frame(f)
        assert dc2.frames_dropped == 0
        assert dc2.journal_exhausted is False

    def test_symbol_filter(self):
        st, dc = self._stream(symbols={"ETHUSDC"})
        for f in _depth_frames(symbol="BTCUSDC", n=2):
            st.ingest_frame(f)
        assert dc.frames_total == 0 and dc.frames_ignored == 2

    def test_no_capture_configured_ignores_depth_frames(self):
        clock, bus, mon = _setup()
        st = MarketStream(mon, now_fn=clock)
        before = st.frames_ignored
        st.ingest_frame(_depth_frames(n=1)[0])
        assert st.frames_ignored == before + 1         # counted, no crash

    def test_malformed_depth_counted(self):
        st, dc = self._stream()
        st.ingest_frame(json.dumps({"e": "depthUpdate", "b": [["x", "y"]]}))
        assert dc.malformed == 1

    def test_depth_url_channels(self):
        url = binance_kline_url(["BTCUSDC"], ["1m"],
                                depth_symbols=["BTCUSDC"])
        assert "btcusdc@kline_1m" in url and "btcusdc@depth" in url

    def test_telemetry_exported_with_stream_gauges(self, tmp_path):
        from ai_crypto_trader_tpu.utils.metrics import MetricsRegistry

        clock, bus, mon = _setup()
        dc = DepthCapture(path=str(tmp_path / "d.jsonl"), ring_max=4,
                          journal_max=4)
        st = MarketStream(mon, now_fn=clock, depth=dc)
        sup = StreamSupervisor(st, now_fn=clock,
                               metrics=MetricsRegistry(now_fn=clock))
        for f in _depth_frames(n=7):
            sup.offer(f)

        async def go():
            await sup.step()

        asyncio.run(go())
        text = sup.metrics.exposition()
        assert "crypto_trader_tpu_depth_frames_total 7" in text
        # 3 frames arrived after the 4-record journal budget was spent
        assert "crypto_trader_tpu_depth_frames_dropped_total 3" in text
        assert "crypto_trader_tpu_depth_capture_ring_fill 1" in text

    def test_alert_coherence_in_process_and_promql(self):
        """DepthCaptureSaturated exists in BOTH rule engines (the PR 1
        coherence guarantee, extended to the capture) — keyed on journal
        exhaustion, NOT ring fill (a keep-last-N ring sits full by
        design)."""
        import yaml

        from ai_crypto_trader_tpu.utils.alerts import AlertManager

        mgr = AlertManager(now_fn=lambda: 1000.0)
        fired = mgr.evaluate({"depth_journal_exhausted": True,
                              "depth_ring_fill": 1.0})
        assert any(a["name"] == "DepthCaptureSaturated" for a in fired)
        # a full ring alone must NOT fire (retention, not loss)
        mgr.evaluate({"depth_journal_exhausted": False,
                      "depth_ring_fill": 1.0})
        assert "DepthCaptureSaturated" not in mgr.active
        # absent state (no capture attached) never fires
        assert not any(a["name"] == "DepthCaptureSaturated"
                       for a in AlertManager(
                           now_fn=lambda: 1000.0).evaluate({}))
        rules = yaml.safe_load(
            open(os.path.join(REPO, "monitoring/alert_rules.yml")))
        names = {r.get("alert") for g in rules["groups"] for r in g["rules"]}
        assert {"DepthCaptureSaturated", "DepthFramesDropping",
                "DepthFeedGaps"} <= names

    def test_launcher_feeds_capture_state_into_alerts(self, tmp_path):
        clock, sys_, sup, ex, counting = _streamed_system()
        dc = DepthCapture(path=str(tmp_path / "d.jsonl"), ring_max=2,
                          journal_max=3)
        sup.stream.depth = dc
        for f in _depth_frames(n=5):
            dc.ingest(json.loads(f))
        state = sys_._alert_state()
        assert state["depth_ring_fill"] == 1.0
        assert state["depth_journal_exhausted"] is True
        # shutdown flushes the buffered depth JSONL tail
        sys_.shutdown()
        from ai_crypto_trader_tpu.utils.journal import replay

        assert replay(str(tmp_path / "d.jsonl"))[1]["replayed"] == 3


# ---------------------------------------------------------------------------
# the degradation ladder (launcher integration) and the stream chaos soak
# ---------------------------------------------------------------------------

def _streamed_system(tmp_path=None, symbols=("BTCUSDC",), n=2400, limit=128,
                     advance=2200, seed0=10):
    from ai_crypto_trader_tpu.shell.launcher import TradingSystem

    clock = Clock()
    series = {s: _series(n=n, seed=seed0 + i, symbol=s)
              for i, s in enumerate(symbols)}
    ex = FakeExchange(series, quote_balance=10_000)
    ex.advance(steps=advance)
    counting = CountingKlines(ex)
    kw = {}
    if tmp_path is not None:
        kw["journal_path"] = str(tmp_path / "stream.journal")
    sys_ = TradingSystem(counting, list(symbols), now_fn=clock, **kw)
    sys_.monitor.kline_limit = limit
    st = MarketStream(sys_.monitor, now_fn=clock)
    sup = StreamSupervisor(st, now_fn=clock, stale_after_s=45.0,
                           max_silence_s=90.0)
    sys_.attach_stream(sup)
    return clock, sys_, sup, ex, counting


class TestDegradationLadder:
    def test_degrade_to_poll_and_hand_back(self):
        """Tentpole (d): no frames → the polling monitor carries the load
        (stream_mode 0); frames arrive → the stream takes over with zero
        REST klines (stream_mode 1); feed goes silent past budget → the
        monitor automatically resumes; frames return → hands back."""
        clock, sys_, sup, ex, counting = _streamed_system()
        ivs = sys_.monitor.intervals
        modes = []

        def mode():
            return sys_.metrics.gauges.get("crypto_trader_tpu_stream_mode")

        async def tick(feed):
            ex.advance(steps=1)
            clock.t += 60.0
            if feed:
                for f in _venue_frames(ex, list(sys_.symbols), ivs,
                                       event_ms=int(clock.t * 1000)):
                    sup.offer(f)
            out = await sys_.tick()
            modes.append(mode())
            return out

        async def go():
            # phase 1: stream never connected → monitor polls REST
            out = await tick(feed=False)
            assert out["published"] == 1
            assert modes[-1] == 0.0
            assert sys_._stream_degraded
            polled_calls = counting.kline_calls
            assert polled_calls > 0
            # phase 2: frames flow → stream takes over; monitor stands down
            await tick(feed=True)              # backfill seed drain (REST)
            assert modes[-1] == 1.0
            seed_calls = counting.kline_calls
            for _ in range(3):
                out = await tick(feed=True)
                assert out["published"] == 1
                assert modes[-1] == 1.0
            assert counting.kline_calls == seed_calls   # ZERO REST klines
            # StreamDegradedToPoll resolved in the rule engine
            assert "StreamDegradedToPoll" not in sys_.alerts.active
            # phase 3: silence past the budget → degrade back to REST poll
            out = await tick(feed=False)
            assert modes[-1] == 0.0
            assert out["published"] == 1       # the monitor carried the tick
            assert "StreamDegradedToPoll" in sys_.alerts.active
            # phase 4: feed recovers → hand back
            await tick(feed=True)
            assert modes[-1] == 1.0
            assert "StreamDegradedToPoll" not in sys_.alerts.active
            # the monitor heartbeat stayed fresh in BOTH modes
            assert clock.t - sys_.heartbeats.beats["monitor"] <= 60.0
            assert clock.t - sys_.heartbeats.beats["stream"] <= 60.0

        asyncio.run(go())

    def test_healthy_stream_does_not_starve_unfed_symbol(self):
        """A universe symbol the subscription isn't feeding (operator URL
        drift, a dropped channel) must keep publishing through REST within
        the lane-staleness budget even while the stream is healthy — the
        full-universe poll never runs at stream_mode 1, so without
        mark_starved the lane would freeze forever, unalerted."""
        clock, bus, mon, ex, counting = _kline_setup()
        st = MarketStream(mon, now_fn=clock)
        sup = StreamSupervisor(st, now_fn=clock)
        ivs = mon.intervals

        async def go():
            # seed: BOTH symbols feed once
            for f in _venue_frames(ex, ["BTCUSDC", "ETHUSDC"], ivs,
                                   event_ms=int(clock.t * 1000)):
                sup.offer(f)
            await sup.step()
            last_eth = mon._last_pub["ETHUSDC"]
            # ETHUSDC's channel silently drops; only BTCUSDC keeps feeding
            for _ in range(4):                   # 240s ≫ the 90s budget
                ex.advance(steps=1)
                clock.t += 60.0
                for f in _venue_frames(ex, ["BTCUSDC"], ivs,
                                       event_ms=int(clock.t * 1000)):
                    sup.offer(f)
                await sup.step()
            assert not sup.degraded(clock.t)     # the stream itself: healthy
            assert mon._last_pub["ETHUSDC"] > last_eth   # lane served anyway
            upd = bus.get("market_data_ETHUSDC")
            assert upd is not None and upd["symbol"] == "ETHUSDC"

        asyncio.run(go())

    def test_quarantined_stream_stage_degrades(self):
        """A crash-looping stream stage is quarantined by the supervisor
        (StageBreaker) and the monitor resumes polling."""
        clock, sys_, sup, ex, counting = _streamed_system()

        async def boom():
            raise RuntimeError("poisoned frame")

        sup.step = boom

        async def go():
            for _ in range(sys_.stage_max_failures):
                ex.advance(steps=1)
                clock.t += 60.0
                out = await sys_.tick()
                assert out["published"] == 1   # monitor carried every tick
            assert sys_.stage_breakers["stream"].quarantined
            assert sys_.metrics.gauges[
                "crypto_trader_tpu_stream_mode"] == 0.0
            # gauges stay TRUTHFUL while quarantined: step() never runs
            # (so its export never fires), but the launcher re-exports
            # every tick — Prometheus must not keep scraping the last
            # healthy-looking values during exactly this outage
            stale_before = sys_.metrics.gauges[
                "crypto_trader_tpu_stream_staleness_seconds"]
            clock.t += 600.0
            ex.advance(steps=1)
            await sys_.tick()
            assert sys_.metrics.gauges[
                "crypto_trader_tpu_stream_staleness_seconds"] >= \
                stale_before + 600.0

        asyncio.run(go())

    def test_degraded_stream_stage_withholds_monitor_heartbeat(self):
        """While the feed is degraded the stream stage must NOT beat the
        monitor heartbeat — during a simultaneous REST outage, ServiceDown
        (monitor) has to be able to fire."""
        clock, sys_, sup, ex, counting = _streamed_system()
        assert sup.degraded(clock.t)                 # never connected
        sys_.heartbeats.beats.pop("monitor", None)

        async def go():
            await sys_._stream_stage()

        asyncio.run(go())
        assert "monitor" not in sys_.heartbeats.beats  # withheld
        # healthy stream → the beat lands
        sup.offer("[]")
        asyncio.run(go())
        assert sys_.heartbeats.beats["monitor"] == clock.t

    def test_pump_read_timeout_bounded_by_silence_budget(self):
        """The pump's per-read timeout is min(read_timeout_s,
        max_silence_s): the watchdog and the transport tear down a silent
        socket on the same clock, so a late frame can't be miscounted as a
        reconnect of a link that never dropped."""
        clock = Clock()
        bus = EventBus(now_fn=clock)
        mon = MarketMonitor(bus, FakeExchange({"BTCUSDC": _series(seed=3)}),
                            symbols=["BTCUSDC"], now_fn=clock, fused=False)
        sup = StreamSupervisor(MarketStream(mon, now_fn=clock), bus=bus,
                               now_fn=clock, read_timeout_s=60.0,
                               max_silence_s=0.02, connect_timeout_s=0.02)
        sleeps = []

        async def fake_sleep(s):
            sleeps.append(s)

        async def slow():
            yield "[]"
            await asyncio.sleep(5)               # silent past the budget
            yield "[]"                           # pragma: no cover

        sources = [slow()]
        sup.source_factory = lambda: sources.pop(0) if sources else None
        sup.sleep = fake_sleep
        asyncio.run(asyncio.wait_for(sup.pump(), 5))
        assert sup.frames_offered == 1           # second read timed out fast
        assert sup.disconnects == 1

    def test_ticker_staleness_fence(self):
        """Satellite: SL/TP maintenance uses the stream's sub-candle ticker
        only while its EXCHANGE EVENT time is fresh; a delayed feed's
        prices are fenced off in favor of the candle close."""
        clock, sys_, sup, ex, counting = _streamed_system()
        sys_.bus.set("market_data_BTCUSDC", {"current_price": 100.0})
        # fresh event time → ticker price wins
        sys_.bus.set("ticker_BTCUSDC", {"price": 101.5,
                                        "event_time": clock.t - 2.0,
                                        "recv_time": clock.t})
        assert sys_._sl_tp_price("BTCUSDC", clock.t) == 101.5
        # stale EVENT time (delayed feed), fresh receive time → fenced off
        sys_.bus.set("ticker_BTCUSDC", {"price": 99.0,
                                        "event_time": clock.t - 60.0,
                                        "recv_time": clock.t})
        assert sys_._sl_tp_price("BTCUSDC", clock.t) == 100.0
        # no ticker at all → candle close
        sys_.bus.delete("ticker_BTCUSDC")
        assert sys_._sl_tp_price("BTCUSDC", clock.t) == 100.0


class StreamSoakRig:
    """Tick-driven stream chaos soak: one venue, a chaos frame feed, the
    full TradingSystem with the supervised stream attached."""

    def __init__(self, tmp_path, symbols, rates, seed, limit=128, n=3000,
                 advance=2200):
        from ai_crypto_trader_tpu.testing.chaos import (
            ChaosFrameSource, FaultSchedule)

        (self.clock, self.system, self.sup, self.ex,
         self.counting) = _streamed_system(tmp_path, symbols, n=n,
                                           limit=limit, advance=advance)
        self.tmp_path = tmp_path
        self.symbols = list(symbols)
        self.chaos = ChaosFrameSource(FaultSchedule(seed=seed, rates=rates),
                                      silence_frames=4 * len(symbols))
        self.modes = []
        self.forced_disconnects = 0

    def mode(self):
        return self.system.metrics.gauges.get("crypto_trader_tpu_stream_mode")

    async def tick(self, feed=True, disconnect=False):
        self.ex.advance(steps=1)
        self.clock.t += 60.0
        if feed:
            frames, dropped_conn = self.chaos.filter(_venue_frames(
                self.ex, self.symbols, self.system.monitor.intervals,
                event_ms=int(self.clock.t * 1000)))
            for f in frames:
                self.sup.offer(f)
            if dropped_conn:
                self.sup.connection_lost("chaos: transport died")
        if disconnect:
            self.sup.connection_lost("chaos: forced disconnect")
            self.forced_disconnects += 1
        out = await self.system.tick()
        self.modes.append(self.mode())
        return out

    async def run(self, ticks, disconnect_at=(), silence_at=()):
        last = None
        for i in range(ticks):
            last = await self.tick(feed=i not in silence_at,
                                   disconnect=i in disconnect_at)
        return last

    async def settle(self, ticks=4):
        """Fault-free cool-down: parity asserted about RECOVERY, not an
        in-flight fault."""
        self.chaos.schedule.rates = {}
        last = None
        for _ in range(ticks):
            last = await self.tick(feed=True)
        return last

    def assert_ring_parity(self):
        """Zero duplicate / out-of-sequence candle rows, every gap
        backfilled: the engine's window mirrors the venue's own REST
        answer bit-for-bit on every warm lane."""
        eng = self.system.monitor._engine
        assert eng is not None, "the fused engine never ran"
        limit = self.system.monitor.kline_limit
        for sym in self.symbols:
            for iv in self.system.monitor.intervals:
                oracle = self.ex.get_klines(sym, iv, limit)
                if len(oracle) < limit:
                    continue                   # lane legitimately warming
                s, f = eng.sym_index[sym], eng.iv_index[iv]
                want = np.asarray([r[1:6] for r in oracle], np.float32)
                np.testing.assert_array_equal(eng._win[s, f], want,
                                              err_msg=f"{sym} {iv}")
                ts = eng._ts[s, f]
                assert (np.diff(ts) > 0).all(), f"{sym} {iv} out of order"
                assert len(set(ts.tolist())) == len(ts), f"{sym} {iv} dup"


STREAM_CHAOS_RATES = {"fs_dup": 0.06, "fs_ooo": 0.06, "fs_malformed": 0.04,
                      "fs_stale": 0.03, "fs_burst": 0.01,
                      "fs_disconnect": 0.01, "fs_silence": 0.01}


def test_stream_chaos_soak_smoke(tmp_path):
    """Tier-1 acceptance soak: ≥3 forced disconnects + a silence window +
    duplicate/out-of-order/malformed/stale injection over ~90 ticks ends
    healthy, with poll-path ring parity, every gap backfilled, and the
    degrade-to-poll → hand-back transition observed via stream_mode."""
    rig = StreamSoakRig(tmp_path, ["BTCUSDC", "ETHUSDC"],
                        rates=STREAM_CHAOS_RATES, seed=5)

    async def go():
        await rig.run(90, disconnect_at={20, 45, 70},
                      silence_at={30, 31})      # > stale_after_s budget
        return await rig.settle()

    final = asyncio.run(go())

    # the feed actually suffered: every fault family observed
    st, sup = rig.sup.stream, rig.sup
    assert sup.disconnects >= 3 and sup.reconnects >= 3
    assert st.dup_frames > 0 and st.ooo_frames > 0
    assert st.malformed_frames > 0
    assert st.gaps > 0 and st.backfills > 0     # every gap REST-repaired

    # degrade-to-poll → hand-back observed via the gauge trajectory
    assert 0.0 in rig.modes and 1.0 in rig.modes
    assert rig.modes[-1] == 1.0                 # handed back, streaming

    # zero REST klines while streaming steady-state: the settle ticks
    # (healthy stream, no faults) performed no transport polls
    calls_before = rig.counting.kline_calls
    asyncio.run(rig.settle(ticks=3))
    assert rig.counting.kline_calls == calls_before

    # ring parity: no duplicate/out-of-sequence rows, gaps all healed
    rig.assert_ring_parity()

    # the system ends healthy
    assert "skipped" not in final
    assert not any(b.quarantined for b in rig.system.stage_breakers.values())
    for stage in ("monitor", "analyzer", "executor", "stream"):
        assert rig.clock.t - rig.system.heartbeats.beats[stage] <= 60.0


@pytest.mark.slow
def test_stream_chaos_soak_full(tmp_path):
    """The full soak: 2 symbols × 400 ticks of frame chaos, 4 forced
    disconnects, two silence windows, plus a hard PROCESS kill mid-run —
    restart recovers the journal, re-attaches a fresh stream (empty books
    → REST backfill seeds → streaming resumes) and still ends in parity."""
    rig = StreamSoakRig(tmp_path, ["BTCUSDC", "ETHUSDC"],
                        rates=STREAM_CHAOS_RATES | {"fs_disconnect": 0.02},
                        seed=9, n=3600, advance=2400)

    async def go():
        await rig.run(200, disconnect_at={40, 90}, silence_at={60, 61})
        # hard kill: journal tail lost, process state abandoned
        rig.system.journal.simulate_crash()
        (rig.clock, rig.system, rig.sup, _, rig.counting) = \
            _streamed_system(rig.tmp_path, rig.symbols, n=3600, limit=128,
                             advance=0)
        # the restarted process rides the SAME venue
        rig.counting.inner = rig.ex
        await rig.system.recover()
        await rig.run(200, disconnect_at={40, 90}, silence_at={120})
        return await rig.settle(6)

    final = asyncio.run(go())
    assert rig.sup.reconnects >= 2
    assert 0.0 in rig.modes and rig.modes[-1] == 1.0
    rig.assert_ring_parity()
    assert "skipped" not in final
    assert not any(b.quarantined for b in rig.system.stage_breakers.values())


class TestStreamAlertCoherence:
    """The new stream alerts exist in BOTH rule engines (in-process +
    PromQL) and the PromQL side only references emitted series — the PR 1
    coherence suite's guarantee, extended to the feed lifecycle."""

    def test_in_process_degrade_rule_fires_and_resolves(self):
        from ai_crypto_trader_tpu.utils.alerts import AlertManager

        mgr = AlertManager(now_fn=lambda: 1000.0)
        fired = mgr.evaluate({"stream_degraded": True})
        assert any(a["name"] == "StreamDegradedToPoll" for a in fired)
        mgr.evaluate({"stream_degraded": False})
        assert "StreamDegradedToPoll" not in mgr.active
        # absent state (no stream attached) never fires
        mgr2 = AlertManager(now_fn=lambda: 1000.0)
        assert not any(a["name"] == "StreamDegradedToPoll"
                       for a in mgr2.evaluate({}))

    def test_promql_twins_exist(self):
        import yaml

        rules = yaml.safe_load(
            open(os.path.join(REPO, "monitoring/alert_rules.yml")))
        names = {r.get("alert") for g in rules["groups"] for r in g["rules"]}
        assert {"StreamDisconnected", "StreamFlapping",
                "StreamDegradedToPoll", "StreamSilent",
                "StreamFrameQueueDropping"} <= names

    def test_supervisor_edge_alerts_reach_the_bus(self):
        clock, sys_, sup, ex, counting = _streamed_system()
        q = sys_.bus.subscribe("alerts")

        async def go():
            sup.offer("[]")
            sup.connection_lost("test edge")
            ex.advance(steps=1)
            clock.t += 60.0
            await sys_.tick()

        asyncio.run(go())
        names = []
        while not q.empty():
            names.append(q.get_nowait()["data"]["name"])
        assert "StreamDisconnected" in names
