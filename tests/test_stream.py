"""Push-feed ingestion: recorded miniTicker frames drive the monitor's
refresh path with the reference's throttle/filter/batch semantics
(`services/market_monitor_service.py:374-403,615`; `auto_trader.py:33-123`)
— zero egress, frames injected through the async-iterator seam."""

import asyncio
import json

import numpy as np
import pytest

from ai_crypto_trader_tpu.data.ingest import OHLCV
from ai_crypto_trader_tpu.data.synthetic import generate_ohlcv
from ai_crypto_trader_tpu.shell.bus import EventBus
from ai_crypto_trader_tpu.shell.exchange import FakeExchange
from ai_crypto_trader_tpu.shell.monitor import MarketMonitor
from ai_crypto_trader_tpu.shell.stream import (
    BinanceStreamSource,
    MarketStream,
    replay_frames,
)


def _series(n=600, seed=5, symbol="BTCUSDC"):
    d = generate_ohlcv(n=n, seed=seed)
    return OHLCV(timestamp=np.arange(n, dtype=np.int64) * 60_000,
                 open=d["open"], high=d["high"], low=d["low"],
                 close=d["close"], volume=d["volume"] * 1000, symbol=symbol)


class Clock:
    def __init__(self):
        self.t = 1_000_000.0

    def __call__(self):
        return self.t


def _frame(*tickers):
    return json.dumps([
        {"e": "24hrMiniTicker", "s": s, "c": str(c), "q": str(q)}
        for (s, c, q) in tickers
    ])


def _setup(symbols=("BTCUSDC", "ETHUSDC")):
    clock = Clock()
    bus = EventBus(now_fn=clock)
    series = {s: _series(seed=10 + i, symbol=s)
              for i, s in enumerate(symbols)}
    ex = FakeExchange(series, quote_balance=10_000)
    ex.advance(steps=600)
    mon = MarketMonitor(bus, ex, symbols=list(symbols), now_fn=clock,
                        kline_limit=128)
    return clock, bus, mon


class TestIngest:
    def test_frame_marks_symbols_and_sets_tickers(self):
        clock, bus, mon = _setup()
        st = MarketStream(mon, now_fn=clock)
        marked = st.ingest_frame(_frame(("BTCUSDC", 50_000, 1e6),
                                        ("ETHUSDC", 3_000, 5e5)))
        assert marked == ["BTCUSDC", "ETHUSDC"]
        assert bus.get("ticker_BTCUSDC")["price"] == 50_000.0
        assert bus.get("ticker_ETHUSDC")["quote_volume"] == 5e5

    @pytest.mark.slow
    def test_throttle_suppresses_hot_symbol(self):
        clock, bus, mon = _setup()
        st = MarketStream(mon, now_fn=clock, throttle_s=5.0)
        assert st.ingest_frame(_frame(("BTCUSDC", 50_000, 1e6)))
        asyncio.run(st.drain())                   # clear the pending set
        clock.t += 1.0
        assert st.ingest_frame(_frame(("BTCUSDC", 50_100, 1e6))) == []
        # the tick itself still lands (executor needs sub-candle prices)
        assert bus.get("ticker_BTCUSDC")["price"] == 50_100.0
        clock.t += 5.0
        assert st.ingest_frame(_frame(("BTCUSDC", 50_200, 1e6))) == \
            ["BTCUSDC"]

    def test_volume_filter(self):
        clock, bus, mon = _setup()
        st = MarketStream(mon, now_fn=clock, min_quote_volume=1e5)
        assert st.ingest_frame(_frame(("BTCUSDC", 50_000, 1e4))) == []
        assert bus.get("ticker_BTCUSDC") is None

    def test_unknown_symbol_ignored(self):
        clock, bus, mon = _setup()
        st = MarketStream(mon, now_fn=clock)
        assert st.ingest_frame(_frame(("DOGEUSDC", 0.1, 1e6))) == []

    def test_malformed_frames_dropped(self):
        clock, bus, mon = _setup()
        st = MarketStream(mon, now_fn=clock)
        assert st.ingest_frame("not json{") == []
        assert st.ingest_frame(json.dumps({"no": "data"})) == []
        assert st.ingest_frame(json.dumps([{"s": "BTCUSDC"}])) == []  # no c
        assert st.frames_in == 3

    def test_combined_stream_envelope(self):
        clock, bus, mon = _setup()
        st = MarketStream(mon, now_fn=clock)
        env = json.dumps({"stream": "!miniTicker@arr",
                          "data": json.loads(_frame(("BTCUSDC", 9e4, 1e6)))})
        assert st.ingest_frame(env) == ["BTCUSDC"]


class TestDrain:
    def test_drain_publishes_through_monitor(self):
        clock, bus, mon = _setup()
        st = MarketStream(mon, now_fn=clock)
        st.ingest_frame(_frame(("BTCUSDC", 50_000, 1e6)))
        n = asyncio.run(st.drain())
        assert n == 1
        upd = bus.get("market_data_BTCUSDC")
        assert upd is not None and upd["symbol"] == "BTCUSDC"
        assert bus.published_counts["market_updates"] == 1

    def test_batch_size_limits_one_drain(self):
        symbols = tuple(f"A{i:02d}USDC" for i in range(8))
        clock, bus, mon = _setup(symbols)
        st = MarketStream(mon, now_fn=clock, batch_size=5)
        st.ingest_frame(_frame(*[(s, 100.0, 1e6) for s in symbols]))
        assert asyncio.run(st.drain()) == 5       # first batch of 5 (:403)
        assert asyncio.run(st.drain()) == 3       # remainder
        assert asyncio.run(st.drain()) == 0


class TestRun:
    def test_replay_source_end_to_end(self):
        clock, bus, mon = _setup()
        st = MarketStream(mon, now_fn=clock)
        frames = [
            _frame(("BTCUSDC", 50_000, 1e6)),
            "garbage",
            _frame(("ETHUSDC", 3_000, 5e5), ("BTCUSDC", 50_050, 1e6)),
        ]
        published = asyncio.run(st.run(replay_frames(frames)))
        assert published == 2                     # BTC throttled on frame 3
        assert bus.get("market_data_BTCUSDC") is not None
        assert bus.get("market_data_ETHUSDC") is not None
        assert st.ticks_in == 3


class TestRealSourceGate:
    def test_binance_source_requires_ws_library(self):
        try:
            import websockets  # noqa: F401
            pytest.skip("websockets installed; gate not reachable")
        except ImportError:
            pass
        with pytest.raises(RuntimeError, match="websockets"):
            BinanceStreamSource()
