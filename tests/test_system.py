"""System-level components: CLI, launcher (TradingSystem), dashboard,
alert manager, profiling timer."""

import asyncio
import json
import os

import numpy as np
import pytest

from ai_crypto_trader_tpu.utils.alerts import AlertManager
from ai_crypto_trader_tpu.utils.profiling import StepTimer



class TestAlerts:
    def test_fire_and_resolve(self):
        am = AlertManager(now_fn=lambda: 0.0)
        fired = am.evaluate({"portfolio_var": 0.15})
        assert any(a["name"] == "HighPortfolioVaR" for a in fired)
        assert "HighPortfolioVaR" in am.active
        fired2 = am.evaluate({"portfolio_var": 0.02})
        assert not fired2 and "HighPortfolioVaR" not in am.active
        assert len(am.history) == 1

    def test_no_refire_while_active(self):
        am = AlertManager(now_fn=lambda: 0.0)
        am.evaluate({"errors_per_min": 5.0})
        again = am.evaluate({"errors_per_min": 5.0})
        assert not again

    def test_stale_market_data(self):
        am = AlertManager(now_fn=lambda: 0.0)
        fired = am.evaluate({"market_data_age_s": 600.0})
        assert any(a["name"] == "StaleMarketData" for a in fired)


class TestProfiling:
    def test_step_timer_records_and_blocks(self):
        import jax.numpy as jnp
        t = StepTimer()
        with t.step() as s:
            s.block(jnp.ones(4) * 2)
        assert len(t.history) == 1 and t.mean >= 0
        with t.step():
            pass  # no registered result is also fine
        assert len(t.history) == 2


class TestDashboard:
    def test_render_sections(self, tmp_path):
        from ai_crypto_trader_tpu.shell.bus import EventBus
        from ai_crypto_trader_tpu.shell.dashboard import (
            dump_state_json, write_dashboard,
        )
        bus = EventBus()
        bus.set("strategy_params", {"stop_loss": 2.0})
        path = write_dashboard(
            str(tmp_path / "d.html"), bus=bus,
            price_series=np.linspace(100, 110, 50),
            equity_curve=np.linspace(10_000, 10_500, 50),
            metrics={"sharpe_ratio": 1.5, "win_rate": 55.0},
            alerts=[{"name": "X", "severity": "info", "description": "d"}],
            now_fn=lambda: 0.0)
        html = open(path).read()
        assert html.count("<svg") == 2
        assert "sharpe_ratio" in html and "stop_loss" in html and "X" in html
        sj = dump_state_json(bus, str(tmp_path / "s.json"))
        assert json.load(open(sj))["strategy_params"]["stop_loss"] == 2.0

    def test_empty_state_renders(self, tmp_path):
        from ai_crypto_trader_tpu.shell.dashboard import write_dashboard
        html = open(write_dashboard(str(tmp_path / "e.html"))).read()
        assert "no data yet" in html


class TestTradingSystem:
    @pytest.mark.slow
    def test_tick_flow_and_status(self):
        from ai_crypto_trader_tpu.config import FrameworkConfig, TradingParams
        from ai_crypto_trader_tpu.shell.exchange import FakeExchange
        from ai_crypto_trader_tpu.shell.launcher import TradingSystem
        from tests.test_shell import _series

        async def go():
            ex = FakeExchange({"BTCUSDC": _series(n=700, seed=9)},
                              quote_balance=10_000)
            ex.advance("BTCUSDC", steps=400)
            clock = {"t": 0.0}
            cfg = FrameworkConfig(trading=TradingParams(
                ai_confidence_threshold=0.0, min_signal_strength=0.0,
                ai_analysis_interval=0.0))
            sys_ = TradingSystem(ex, ["BTCUSDC"], config=cfg,
                                 now_fn=lambda: clock["t"])
            for _ in range(60):
                ex.advance("BTCUSDC")
                clock["t"] += 60.0
                await sys_.tick()
            st = sys_.status()
            assert st["channels"]["market_updates"] == 60
            assert st["channels"]["trading_signals"] == 60
            assert "USDC" in st["balances"]
            assert "portfolio_value_usd" in sys_.metrics.exposition()
        asyncio.run(go())


class TestCLI:
    @pytest.mark.slow
    def test_fetch_backtest_list_analyze(self, tmp_path, monkeypatch):
        from ai_crypto_trader_tpu import cli
        monkeypatch.chdir(tmp_path)
        cli.main(["fetch", "--symbol", "TESTUSDC", "--days", "1"])
        assert os.path.exists("backtesting/data/market/TESTUSDC/TESTUSDC_1m.csv")
        cli.main(["backtest", "--symbol", "TESTUSDC", "--days", "1"])
        results = os.listdir("backtesting/results")
        assert len(results) == 1
        cli.main(["list"])
        cli.main(["analyze", "--file",
                  os.path.join("backtesting/results", results[0])])
        r = json.load(open(os.path.join("backtesting/results", results[0])))
        assert "sharpe_ratio" in r and r["candles_per_sec"] > 0

    def test_registry_command(self, tmp_path, monkeypatch, capsys):
        from ai_crypto_trader_tpu import cli
        from ai_crypto_trader_tpu.strategy.registry import ModelRegistry
        p = str(tmp_path / "reg.json")
        reg = ModelRegistry(path=p)
        v = reg.register("strategy_params", {"a": 1.0})
        reg.update_performance(v, {"sharpe_ratio": 2.0})
        cli.main(["registry", "--path", p])
        out = capsys.readouterr().out
        assert v in out
        cli.main(["registry", "--path", p, "--best"])
        assert '"sharpe_ratio": 2.0' in capsys.readouterr().out

    def test_trade_requires_paper(self, capsys):
        from ai_crypto_trader_tpu import cli
        cli.main(["trade", "--ticks", "1"])
        assert "use --paper" in capsys.readouterr().out
