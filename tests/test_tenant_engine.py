"""Tenants as a batch axis (ops/tenant_engine.py + the vmapped loadgen rim).

Covers:
  * veto-gate PARITY: the traced per-tenant gate program agrees
    gate-for-gate with `TradeExecutor.veto_reason` + its sizing gate on a
    randomized sweep of signals (NaN/zero-price poisoned payloads
    included), randomized tenant params and position state — the flight
    recorder vocabulary (`obs.flightrec.GATES` / `VETO_ORDER`) stays the
    single source of truth;
  * the one-dispatch/one-sync/zero-recompile CONTRACT on the meshprof
    sentinel counter (the PR 12 pattern), cost card + donation verifier,
    plus the N-changes-recompile NEGATIVE test (an undeclared tenant-axis
    shape change is counted and alerted);
  * pad/mask layout-card assertions for ragged tenant counts on the 8-way
    test mesh, sharded ≡ single-device (`-m slow`);
  * the HARNESS parity oracle: the vmapped loadgen path pins decisions
    (verdict/gate, execution, quantity) tick-for-tick against the
    per-lane Python object path on identical seeds — veto-heavy default
    params AND a permissive config that opens real venue positions;
  * sequential within-tick semantics (the symbol-axis scan carry:
    max_positions and balance updates are visible to later symbols);
  * venue-truth corrections (`revert_entry`) re-seed without recompiling.
"""

import asyncio

import numpy as np
import pytest

from ai_crypto_trader_tpu.config import TradingParams
from ai_crypto_trader_tpu.obs.flightrec import GATES, VETO_ORDER
from ai_crypto_trader_tpu.ops import tenant_engine
from ai_crypto_trader_tpu.ops.tenant_engine import (
    EXECUTABLE,
    GATE_NAME,
    NO_DECISION,
    TenantEngine,
)
from ai_crypto_trader_tpu.utils import devprof, meshprof
from ai_crypto_trader_tpu.utils.metrics import MetricsRegistry

SYMS = [f"P{i:03d}USDC" for i in range(4)]


def _feats(eng, price, signal, strength, vol, avol, valid=None):
    """[S]-padded feature columns from per-symbol lists."""
    S, n = eng.S, len(price)
    pad = lambda a, dt: np.asarray(        # noqa: E731
        list(a) + [0] * (S - n), dt)
    return {
        "price": pad(price, np.float32),
        "signal": pad(signal, np.int32),
        "strength": pad(strength, np.float32),
        "volatility": pad(vol, np.float32),
        "avg_volume": pad(avol, np.float32),
        "valid": pad(valid if valid is not None else [True] * n,
                     bool),
    }


class TestGateVocabulary:
    def test_gate_ids_index_the_flightrec_vocabulary(self):
        for name, gid in tenant_engine.GATE_ID.items():
            assert GATES[gid] == name
        assert set(VETO_ORDER) <= set(GATES)
        assert EXECUTABLE == -1 and NO_DECISION == -2


class TestGateParity:
    """Randomized sweep: traced gates == the executor's real decision
    path, gate-for-gate, including NaN/zero-price poisoned payloads."""

    PARAM_GRID = [
        TradingParams(),
        TradingParams(ai_confidence_threshold=0.5, min_signal_strength=50.0,
                      max_positions=2),
        TradingParams(ai_confidence_threshold=0.3, min_signal_strength=20.0,
                      min_trade_amount=400.0),
    ]

    def _random_features(self, rng):
        """One symbol's feature row, poisoned ~20% of the time."""
        price = float(rng.choice(
            [rng.uniform(10.0, 500.0), rng.uniform(10.0, 500.0),
             rng.uniform(10.0, 500.0), rng.uniform(10.0, 500.0),
             0.0, -5.0, np.nan]))
        strength = float(rng.choice(
            [rng.uniform(0.0, 120.0), rng.uniform(0.0, 120.0),
             rng.uniform(0.0, 120.0), np.nan]))
        vol = float(rng.choice([rng.uniform(0.0, 0.05),
                                rng.uniform(0.0, 0.05), np.nan]))
        avol = float(rng.choice([rng.uniform(0.0, 120_000.0),
                                 rng.uniform(0.0, 120_000.0), np.nan]))
        sig = int(rng.choice([1, 1, -1, 0]))
        return price, sig, strength, vol, avol

    @staticmethod
    def _signal_dict(sym, price, sig, strength, vol, avol):
        """The payload the analyzer would publish for these features:
        deterministic backend verdict (TechnicalPolicyBackend rule)."""
        sig_str = {1: "BUY", -1: "SELL", 0: "NEUTRAL"}[sig]
        decision = sig_str if sig_str in ("BUY", "SELL") else "HOLD"
        # the backend rounds its JSON confidence to 3 decimals
        confidence = min(strength / 100.0, 1.0) * 0.9
        confidence = round(confidence, 3) if np.isfinite(confidence) \
            else confidence
        return {"symbol": sym, "current_price": price, "signal": sig_str,
                "signal_strength": strength, "volatility": vol,
                "avg_volume": avol, "decision": decision,
                "confidence": confidence}

    def _oracle_case(self, trading, balance, open_syms, pending_syms,
                     signals):
        """Run one tenant-case through the REAL executor, symbol by
        symbol (the sequential drain): returns per-symbol (gate | None,
        quantity | None) from a capturing flight recorder."""
        from ai_crypto_trader_tpu.data.ingest import OHLCV
        from ai_crypto_trader_tpu.obs.flightrec import FlightRecorder
        from ai_crypto_trader_tpu.shell.bus import EventBus
        from ai_crypto_trader_tpu.shell.exchange import FakeExchange
        from ai_crypto_trader_tpu.shell.executor import TradeExecutor

        series = {}
        for sym, s in signals.items():
            p = s["current_price"]
            p = p if np.isfinite(p) and p > 0 else 1.0   # vetoed anyway
            series[sym] = OHLCV(
                timestamp=np.arange(4, dtype=np.int64) * 60_000,
                open=np.full(4, p), high=np.full(4, p),
                low=np.full(4, p), close=np.full(4, p),
                volume=np.full(4, 1.0), symbol=sym)
        from types import SimpleNamespace

        venue = FakeExchange(series, quote_balance=balance)
        fr = FlightRecorder()
        ex = TradeExecutor(EventBus(), venue, trading=trading, flightrec=fr)
        ex.active_trades = {sym: SimpleNamespace() for sym in open_syms}
        ex.pending_intents = {f"x-{i}": {"symbol": sym}
                              for i, sym in enumerate(pending_syms)}

        out = {}
        for sym in sorted(signals):
            rid = f"d-{sym}"
            sig = dict(signals[sym], decision_id=rid)
            trade = asyncio.run(ex.handle_signal(sig))
            rec = fr._by_id.get(rid)
            if trade is not None:
                out[sym] = (None, trade.quantity)
            elif rec is not None and rec["status"] == "vetoed":
                out[sym] = (rec["gate"], None)
            else:                       # vetoed before any recording
                out[sym] = (ex.veto_reason(sig), None)
        return out

    def test_randomized_sweep_gate_for_gate(self):
        rng = np.random.default_rng(20260805)
        rounds, n_cases = 6, 8
        checked = 0
        seen_gates = set()
        for r in range(rounds):
            rows = [self._random_features(rng) for _ in SYMS]
            feats = _feats(
                None or type("E", (), {"S": 8})(),  # placeholder, below
                [x[0] for x in rows], [x[1] for x in rows],
                [x[2] for x in rows], [x[3] for x in rows],
                [x[4] for x in rows])
            cases = []
            for i in range(n_cases):
                trading = self.PARAM_GRID[int(rng.integers(
                    len(self.PARAM_GRID)))]
                balance = float(rng.uniform(50.0, 20_000.0))
                open_syms = [s for s in SYMS if rng.random() < 0.25]
                pending_syms = [s for s in SYMS
                                if s not in open_syms and rng.random() < 0.15]
                cases.append((trading, balance, open_syms, pending_syms))

            eng = TenantEngine(SYMS, n_cases)
            for i, (trading, balance, open_syms, pending_syms) in \
                    enumerate(cases):
                eng.set_tenant(
                    i, balance=balance, open_symbols=open_syms,
                    pending_symbols=pending_syms,
                    conf_threshold=trading.ai_confidence_threshold,
                    min_strength=trading.min_signal_strength,
                    max_positions=trading.max_positions,
                    min_trade=trading.min_trade_amount)
            out = eng.decide(feats)

            for i, (trading, balance, open_syms, pending_syms) in \
                    enumerate(cases):
                signals = {sym: self._signal_dict(sym, *rows[s])
                           for s, sym in enumerate(SYMS)}
                oracle = self._oracle_case(trading, balance, open_syms,
                                           pending_syms, signals)
                for s, sym in enumerate(SYMS):
                    gate_py, qty_py = oracle[sym]
                    gid = int(out["gate"][i, s])
                    gate_vm = None if gid == EXECUTABLE \
                        else GATE_NAME.get(gid, gid)
                    assert gate_vm == gate_py, (
                        f"round {r} tenant {i} {sym}: vmapped={gate_vm} "
                        f"oracle={gate_py} features={rows[s]} "
                        f"params={trading} balance={balance} "
                        f"open={open_syms} pending={pending_syms}")
                    seen_gates.add(gate_py)
                    if gate_py is None:
                        assert qty_py == pytest.approx(
                            float(out["qty"][i, s]), rel=1e-4)
                    checked += 1
        assert checked == rounds * n_cases * len(SYMS)
        # the sweep exercised a meaningful slice of the vocabulary
        # (poisoned payloads AND executable decisions included)
        assert {"nan_gate", None} <= seen_gates
        assert len(seen_gates - {None}) >= 5, seen_gates

    def test_sequential_semantics_max_positions_and_balance(self):
        """Symbol k's entry is visible to symbol k+1 in the SAME tick —
        the scan carry mirrors the executor's sequential drain."""
        eng = TenantEngine(SYMS, 1,
                           trading=TradingParams(ai_confidence_threshold=0.5,
                                                 min_signal_strength=50.0,
                                                 max_positions=2))
        feats = _feats(eng, [100.0] * 4, [1] * 4, [90.0] * 4,
                       [0.015] * 4, [60_000.0] * 4)
        out = eng.decide(feats)
        gates = [int(g) for g in out["gate"][0]][:4]
        # first two executable, the rest hit the cap WITHIN the tick
        assert gates[0] == EXECUTABLE and gates[1] == EXECUTABLE
        assert GATE_NAME[gates[2]] == "max_positions"
        assert GATE_NAME[gates[3]] == "max_positions"
        # the balance carry funded both entries (fee included)
        spent = float(out["size"][0, 0] + out["size"][0, 1]) * 1.001
        assert eng.balances()[0] == pytest.approx(10_000.0 - spent, rel=1e-5)

    def test_revert_entry_refunds_and_reseeds(self):
        eng = TenantEngine(SYMS, 1,
                           trading=TradingParams(ai_confidence_threshold=0.5,
                                                 min_signal_strength=50.0))
        feats = _feats(eng, [100.0], [1], [90.0], [0.015], [60_000.0])
        out = eng.decide(feats)
        assert (0, 0) in eng.executable(out)
        bal = eng.balances()[0]
        eng.revert_entry(0, SYMS[0])
        assert eng._need_seed
        assert eng.balances()[0] == pytest.approx(10_000.0, rel=1e-5)
        assert eng.balances()[0] > bal
        # next decide re-seeds (a transfer) and the symbol is entryable
        out2 = eng.decide(feats)
        assert int(out2["gate"][0, 0]) == EXECUTABLE


class TestContract:
    """One dispatch + one sync per decide, zero steady-state recompiles on
    the meshprof sentinel, cost card + donation verified — and the
    NEGATIVE: an undeclared tenant-axis shape change is counted+alerted."""

    def test_one_dispatch_one_sync_zero_recompile(self, monkeypatch):
        syncs = {"n": 0}
        real_read = tenant_engine.host_read

        def counting_read(tree):
            syncs["n"] += 1
            return real_read(tree)

        monkeypatch.setattr(tenant_engine, "host_read", counting_read)
        m = MetricsRegistry()
        mp = meshprof.MeshProf(metrics=m)
        with devprof.use(devprof.DevProf(metrics=m)) as dp, \
                meshprof.use(mp):
            eng = TenantEngine(SYMS, 48)     # pads to 64
            feats = _feats(eng, [100.0, 50.0, 200.0, 80.0], [1, -1, 1, 0],
                           [90.0, 70.0, 40.0, 90.0], [0.015] * 4,
                           [60_000.0] * 4)
            eng.decide(feats)                # compile + card (cold)
            assert syncs["n"] == 1
            assert eng.last_stats["dispatches"] == 1
            assert eng.last_stats["tenant_pad"] == 64
            card = dp.cards["tenant_engine"]
            assert card.error is None and card.flops > 0
            assert card.donation_ok is True
            assert dp.donation_failures == []
            # layout card registered through the Partitioner seam
            assert mp.layouts["tenant_engine"].population == 64
            assert mp.layouts["tenant_engine"].pad == 0

            eng.decide(feats)                # steady state
            assert syncs["n"] == 2
            assert mp.recompiles.steady_total() == 0, mp.recompiles.status()
            assert mp.recompiles.windows["tenant_engine"] == 2
            assert mp.transfers.total() == 0
            # donated carry: the previous pop buffers were freed
            assert not eng._need_seed and eng.full_seeds == 1

    def test_n_changes_recompile_negative(self):
        """A tenant-axis shape change NOT declared cold is a counted
        steady-state recompile + SteadyStateRecompile alert (the
        sentinel's production invariant, PR 12 pattern)."""
        m = MetricsRegistry()
        mp = meshprof.MeshProf(metrics=m)
        with meshprof.use(mp):
            eng = TenantEngine(SYMS, 8)
            feats = _feats(eng, [100.0] * 4, [0] * 4, [50.0] * 4,
                           [0.01] * 4, [50_000.0] * 4)
            eng.decide(feats)
            eng.decide(feats)
            assert mp.recompiles.steady_total() == 0
            # resize the tenant axis but FORGE the cold declaration —
            # exactly the bug the sentinel exists to catch
            eng.configure(24)                # pads to 32: a new shape
            eng._cold = False
            eng.decide(feats)
            assert mp.recompiles.steady["tenant_engine"] >= 1
            assert "tenant_engine" in mp.recompiles.alerted
            assert "tenant_engine" in mp.alert_state()[
                "steady_recompile_programs"]
        # declared-cold resizes never count (the ramp's legitimate path)
        m2 = MetricsRegistry()
        mp2 = meshprof.MeshProf(metrics=m2)
        with meshprof.use(mp2):
            eng2 = TenantEngine(SYMS, 8)
            eng2.decide(feats)
            eng2.decide(feats)
            eng2.configure(24)               # _cold=True by design
            eng2.decide(feats)
            assert mp2.recompiles.steady_total() == 0


@pytest.mark.slow
class TestMeshLayout:
    def test_ragged_tenants_pad_mask_on_mesh8(self, mesh8):
        """Tenant count 10 on the 8-way mesh: population_eval pads 10→16
        (pad_fraction 0.375), the layout card records it, and the sharded
        decisions equal the single-device ones."""
        from ai_crypto_trader_tpu.parallel import MeshPartitioner

        feats_src = ([100.0, 50.0, 200.0, 80.0], [1, -1, 1, 1],
                     [90.0, 70.0, 40.0, 85.0], [0.015, 0.01, 0.03, 0.02],
                     [60_000.0, 1_000.0, 60_000.0, 55_000.0])
        m = MetricsRegistry()
        mp = meshprof.MeshProf(metrics=m)
        with meshprof.use(mp):
            part = MeshPartitioner(mesh8)
            eng = TenantEngine(SYMS, 10, partitioner=part, pad_pow2=False)
            eng.set_tenant(3, open_symbols=[SYMS[0]])
            eng.set_tenant(7, conf_threshold=0.3, min_strength=20.0)
            out = eng.decide(_feats(eng, *feats_src))
            card = mp.layouts["tenant_engine"]
            assert card.population == 10 and card.pad == 6
            assert card.devices == 8
            assert card.pad_fraction == pytest.approx(0.375)
            assert out["gate"].shape[0] == 10
            # ragged carry regression: population_eval SLICES the padded
            # all-gather back to 10, so feeding the carry straight into
            # the next dispatch would change input sharding and retrace
            # EVERY tick — the engine must re-seed from the mirror
            # instead (found by the verify drive; zero steady recompiles
            # across repeat dispatches is the pinned contract)
            eng.decide(_feats(eng, *feats_src))
            eng.decide(_feats(eng, *feats_src))
            assert mp.recompiles.steady_total() == 0, \
                mp.recompiles.status()
            assert mp.recompiles.windows["tenant_engine"] == 3
        single = TenantEngine(SYMS, 10, pad_pow2=False)
        single.set_tenant(3, open_symbols=[SYMS[0]])
        single.set_tenant(7, conf_threshold=0.3, min_strength=20.0)
        ref = single.decide(_feats(single, *feats_src))
        for k in ("gate", "decision", "exec"):
            np.testing.assert_array_equal(out[k], ref[k])
        for k in ("confidence", "size", "qty", "sl_pct", "tp_pct"):
            np.testing.assert_allclose(out[k], ref[k], rtol=1e-6,
                                       equal_nan=True)
        np.testing.assert_allclose(eng.balances(), single.balances(),
                                   rtol=1e-6)


class TestHarnessParityOracle:
    """The acceptance oracle: vmapped loadgen decisions (verdict/gate,
    execution, quantity) pinned tick-for-tick against the per-lane Python
    object path on identical seeds."""

    def _collect_vmapped(self, cfg, ticks):
        from ai_crypto_trader_tpu.testing.loadgen import (
            SyntheticTenantTraffic)

        traffic = SyntheticTenantTraffic(cfg)
        decisions = {}                   # (t, tenant, symbol) -> record

        async def go():
            for _ in range(ticks):
                await traffic.tick(timed=False)
                eng = traffic.tenant_engine
                out = eng.last_out
                if out is None:
                    continue
                t = traffic.clock["t"]
                for i in range(eng.n_tenants):
                    for s, sym in enumerate(traffic.symbols):
                        gid = int(out["gate"][i, s])
                        if gid == NO_DECISION:
                            continue
                        decisions[(t, i, sym)] = {
                            "gate": (None if gid == EXECUTABLE
                                     else GATE_NAME[gid]),
                            "confidence": float(out["confidence"][i, s]),
                            "qty": float(out["qty"][i, s]),
                        }
        asyncio.run(go())
        return traffic, decisions

    def _collect_objects(self, cfg, ticks):
        from ai_crypto_trader_tpu.obs.flightrec import FlightRecorder
        from ai_crypto_trader_tpu.testing.loadgen import (
            SyntheticTenantTraffic)

        traffic = SyntheticTenantTraffic(cfg)
        frs = []
        for lane in traffic.lanes:
            fr = FlightRecorder(now_fn=traffic._now)
            lane.analyzer.flightrec = fr
            lane.executor.flightrec = fr
            frs.append(fr)

        async def go():
            for _ in range(ticks):
                await traffic.tick(timed=False)
        asyncio.run(go())

        decisions = {}
        for i, fr in enumerate(frs):
            for rec in fr.query(limit=0):
                if rec["status"] == "open":
                    continue             # published but never terminal
                verdict = rec.get("verdict") or {}
                ex = rec.get("exec") or {}
                fills = rec.get("fills") or []
                decisions[(rec["t"], i, rec["symbol"])] = {
                    "gate": rec["gate"],
                    "confidence": verdict.get("confidence"),
                    "qty": (fills[0]["quantity"] if fills
                            else ex.get("quantity")),
                }
        return traffic, decisions

    def _compare(self, trading, ticks=6):
        from ai_crypto_trader_tpu.testing.loadgen import LoadConfig

        kw = dict(tenants=3, symbols=3, ticks=ticks, warmup_ticks=0,
                  window=64, seed=5, trading=trading)
        vm_traffic, vm = self._collect_vmapped(
            LoadConfig(mode="vmapped", **kw), ticks)
        obj_traffic, obj = self._collect_objects(
            LoadConfig(mode="objects", **kw), ticks)
        assert vm, "vmapped path produced no decisions"
        assert set(vm) == set(obj), (
            f"decision keys diverge: only_vm={set(vm) - set(obj)} "
            f"only_obj={set(obj) - set(vm)}")
        executed = 0
        for key in sorted(vm):
            assert vm[key]["gate"] == obj[key]["gate"], \
                (key, vm[key], obj[key])
            if obj[key]["confidence"] is not None:
                assert vm[key]["confidence"] == pytest.approx(
                    obj[key]["confidence"], rel=1e-5, abs=1e-6), key
            if vm[key]["gate"] is None:
                executed += 1
                assert obj[key]["qty"] == pytest.approx(
                    vm[key]["qty"], rel=1e-4), key
        return vm_traffic, obj_traffic, executed

    def test_parity_default_params_veto_heavy(self):
        vm_t, obj_t, executed = self._compare(TradingParams())
        # the decision fan-out is the load; default gates veto everything
        assert executed == 0
        assert vm_t.tenant_engine.open_positions() == 0

    def test_venue_balance_reanchors_engine_state(self):
        """A venue-side credit the engine's entry model never saw (a
        protective SL/TP fill on a later candle) re-anchors the tenant's
        device balance on venue truth at the next reconcile — the
        object-lane executors size from exactly this balance."""
        from ai_crypto_trader_tpu.testing.loadgen import (
            LoadConfig, SyntheticTenantTraffic)

        cfg = LoadConfig(mode="vmapped", tenants=2, symbols=3, ticks=4,
                         warmup_ticks=0, window=64, seed=5,
                         trading=TradingParams(ai_confidence_threshold=0.1,
                                               min_signal_strength=10.0))
        traffic = SyntheticTenantTraffic(cfg)

        async def go(n):
            for _ in range(n):
                await traffic.tick(timed=False)
        asyncio.run(go(8))
        assert traffic._vm_lanes, "no tenant ever traded — nothing to sync"
        n = next(iter(traffic._vm_lanes))
        lane = traffic._vm_lanes[n]
        # in lockstep the engine already mirrors the venue (within f32)
        assert traffic.tenant_engine.balances()[n] == pytest.approx(
            lane.venue.get_balances()["USDC"], rel=1e-4)
        # a protective fill credits quote venue-side; the engine model
        # never sees it — the next tick's reconcile must re-anchor
        lane.venue.balances["USDC"] += 1234.5
        asyncio.run(go(1))
        assert traffic.tenant_engine.balances()[n] == pytest.approx(
            lane.venue.get_balances()["USDC"], rel=1e-4)
        # within-tolerance f32 wobble never thrashes the re-seed path
        assert not traffic.tenant_engine.sync_balance(
            n, float(traffic.tenant_engine.balances()[n]) * (1 + 1e-7))

    def test_venue_side_close_frees_engine_position_slot(self):
        """A position the executor no longer holds (protective SL/TP
        filled venue-side, exit sold) must clear the engine's open flag —
        a stale True would veto every re-entry via position_open and
        consume a max_positions slot in the scan carry forever."""
        from ai_crypto_trader_tpu.testing.loadgen import (
            LoadConfig, SyntheticTenantTraffic)

        cfg = LoadConfig(mode="vmapped", tenants=2, symbols=3, ticks=4,
                         warmup_ticks=0, window=64, seed=5,
                         trading=TradingParams(ai_confidence_threshold=0.1,
                                               min_signal_strength=10.0))
        traffic = SyntheticTenantTraffic(cfg)

        async def go(n):
            for _ in range(n):
                await traffic.tick(timed=False)
        asyncio.run(go(8))
        assert traffic._vm_lanes, "no tenant ever traded"
        n = next(iter(traffic._vm_lanes))
        lane = traffic._vm_lanes[n]
        sym, trade = next(iter(lane.executor.active_trades.items()))
        s = traffic.tenant_engine.sym_index[sym]
        assert traffic.tenant_engine._state_np["open"][n, s]
        # simulate a venue-side closure: the executor pops the trade and
        # the venue credits the sale proceeds
        lane.executor.active_trades.pop(sym)
        lane.venue.balances["USDC"] += trade.quantity * trade.entry_price
        asyncio.run(go(1))
        eng = traffic.tenant_engine
        assert not eng._state_np["open"][n, s], \
            "venue-side close left the engine position flag stale"
        assert eng.balances()[n] == pytest.approx(
            lane.venue.get_balances()["USDC"], rel=1e-4)

    def test_parity_permissive_params_real_entries(self):
        # thresholds low enough that the synthetic market's BUY ticks
        # execute (reference strengths run 35-50 on this window), cap 2
        # so the within-tick max_positions carry is exercised too
        trading = TradingParams(ai_confidence_threshold=0.1,
                                min_signal_strength=10.0, max_positions=2)
        vm_t, obj_t, executed = self._compare(trading, ticks=8)
        assert executed > 0, "permissive config never executed — the " \
                             "oracle exercised no entry path"
        # the venue-side books agree lane-for-lane: same symbols held,
        # same client-order-id namespace partitioning
        for i, obj_lane in enumerate(obj_t.lanes):
            vm_lane = obj_t.lanes and vm_t._vm_lanes.get(i)
            obj_syms = sorted(obj_lane.executor.active_trades)
            vm_syms = (sorted(vm_lane.executor.active_trades)
                       if vm_lane else [])
            assert obj_syms == vm_syms, f"lane {i}"
            if vm_lane:
                for sym, trade in vm_lane.executor.active_trades.items():
                    assert trade.entry_coid.startswith(f"ld{i}-ent-{sym}")
        # engine device state mirrors the venue books
        assert vm_t.tenant_engine.open_positions() == sum(
            len(lane.executor.active_trades)
            for lane in vm_t._vm_lanes.values())
