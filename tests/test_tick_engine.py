"""Fused tick engine (ops/tick_engine.py): parity with the per-symbol
monitor path, the one-dispatch/one-sync contract, ring-buffer delta
uploads, recompile-freedom, and the batched prediction path.

The parity class is the tentpole's safety net: the fused engine must
publish byte-for-byte the same market_updates payload the per-symbol
`_features_from_klines` path produced (all fields, warm-up and
full-window cases).  The contract class is the tier-1 regression guard:
a change that reintroduces per-symbol dispatches or extra host syncs on
the poll path fails here, not in a quarterly bench run.
"""

import asyncio

import numpy as np
import pytest

from ai_crypto_trader_tpu.data.ingest import OHLCV
from ai_crypto_trader_tpu.data.synthetic import generate_ohlcv
from ai_crypto_trader_tpu.ops import tick_engine
from ai_crypto_trader_tpu.ops.tick_engine import TickEngine
from ai_crypto_trader_tpu.shell.bus import EventBus
from ai_crypto_trader_tpu.shell.exchange import FakeExchange
from ai_crypto_trader_tpu.shell.monitor import MarketMonitor

LIMIT = 128          # same compiled shape bucket as tests/test_stream.py


def _series(n=900, seed=7, symbol="BTCUSDC"):
    d = generate_ohlcv(n=n, seed=seed)
    return OHLCV(timestamp=np.arange(n, dtype=np.int64) * 60_000,
                 open=d["open"], high=d["high"], low=d["low"],
                 close=d["close"], volume=d["volume"] * 1000, symbol=symbol)


def _exchange(symbols=("BTCUSDC", "ETHUSDC"), n=900, advance=700):
    ex = FakeExchange({s: _series(n=n, seed=7 + i, symbol=s)
                       for i, s in enumerate(symbols)})
    ex.advance(steps=advance)
    return ex


def _monitors(ex, symbols, clock, structure=False):
    pair = []
    for fused in (True, False):
        bus = EventBus()
        if structure:
            bus.set("strategy_structure", {
                "rules": {"oscillator_consensus": 1.0,
                          "trend_confirmation": 1.0},
                "buy_threshold": 0.05, "sell_threshold": 0.05,
                "version": "v9"})
        pair.append(MarketMonitor(bus, ex, symbols=list(symbols),
                                  now_fn=lambda: clock["t"],
                                  kline_limit=LIMIT, fused=fused))
    return pair


def _assert_payload_equal(fused: dict, legacy: dict, where: str):
    assert set(fused) == set(legacy), \
        (where, set(fused) ^ set(legacy))
    for k, b in legacy.items():
        a = fused[k]
        if isinstance(b, float):
            assert a == pytest.approx(b, rel=1e-4, abs=1e-6), (where, k, a, b)
        elif isinstance(b, dict):
            for kk, bv in b.items():
                assert a[kk] == pytest.approx(bv, rel=1e-4, abs=1e-6), \
                    (where, k, kk, a[kk], bv)
        else:
            assert a == b, (where, k, a, b)


class TestParity:
    def test_fused_matches_per_symbol_path_all_fields(self):
        """Full-window 1m/3m/5m + WARMING 15m (47/128 candles): every
        published field — scalars, labels, per-interval columns, volume
        profile, confluence, structure view — identical on both paths,
        and warming frames contribute no columns on either."""
        async def go():
            symbols = ("BTCUSDC", "ETHUSDC")
            ex = _exchange(symbols)
            clock = {"t": 0.0}
            mf, ml = _monitors(ex, symbols, clock, structure=True)
            assert await mf.poll(force=True) == 2
            assert await ml.poll(force=True) == 2
            for s in symbols:
                uf = mf.bus.get(f"market_data_{s}")
                ul = ml.bus.get(f"market_data_{s}")
                # warming 15m frame: no columns, both paths
                assert "rsi_15m" not in ul and "rsi_15m" not in uf
                assert "rsi_3m" in uf and "signal_5m" in uf
                assert "structure_version" in uf
                _assert_payload_equal(uf, ul, s)
                # historical data stored for every non-warming frame
                for iv in ("1m", "3m", "5m"):
                    assert (mf.bus.get(f"historical_data_{s}_{iv}")
                            == ml.bus.get(f"historical_data_{s}_{iv}"))
            # warmup bookkeeping matches
            for s in symbols:
                assert (mf.bus.get(f"monitor_warmup_{s}")
                        == ml.bus.get(f"monitor_warmup_{s}"))

        asyncio.run(go())

    def test_parity_holds_across_incremental_ticks(self):
        """After the seed poll, subsequent polls ride the ring-buffer
        delta path — values must still match a from-scratch compute."""
        async def go():
            symbols = ("BTCUSDC",)
            ex = _exchange(symbols)
            clock = {"t": 0.0}
            mf, ml = _monitors(ex, symbols, clock)
            await mf.poll(force=True)
            await ml.poll(force=True)
            for _ in range(4):
                ex.advance(steps=1)
                clock["t"] += 60.0
                assert await mf.poll() == 1
                assert await ml.poll() == 1
                _assert_payload_equal(mf.bus.get("market_data_BTCUSDC"),
                                      ml.bus.get("market_data_BTCUSDC"),
                                      f"t={clock['t']}")
                assert not mf._engine.last_stats["full_seed"]

        asyncio.run(go())

    def test_primary_warming_publishes_nothing(self):
        async def go():
            ex = _exchange(("BTCUSDC",), n=900, advance=50)  # < LIMIT candles
            clock = {"t": 0.0}
            mf, ml = _monitors(ex, ("BTCUSDC",), clock)
            assert await mf.poll(force=True) == 0
            assert await ml.poll(force=True) == 0
            assert mf.bus.get("market_data_BTCUSDC") is None
            # neither path stores primary history for an unpublished symbol
            assert mf.bus.get("historical_data_BTCUSDC_1m") is None
            assert (mf.bus.get("monitor_warmup_BTCUSDC")
                    == ml.bus.get("monitor_warmup_BTCUSDC"))

        asyncio.run(go())

    def test_fetch_failure_still_publishes_earlier_symbols(self):
        """Per-symbol-loop failure parity: a raising fetch (the resilient
        adapter's ExchangeUnavailable) must not blank the whole batch —
        symbols fetched before the failure still publish, and the
        exception re-raises for the launcher's skip-and-alert path."""
        async def go():
            symbols = ("BTCUSDC", "ETHUSDC")
            ex = _exchange(symbols)
            clock = {"t": 0.0}
            bus = EventBus()
            mon = MarketMonitor(bus, ex, symbols=list(symbols),
                                now_fn=lambda: clock["t"],
                                kline_limit=LIMIT, fused=True)
            boom = RuntimeError("venue down")
            real = ex.get_klines

            def flaky(symbol, interval="1m", limit=100):
                if symbol == "ETHUSDC":
                    raise boom
                return real(symbol, interval, limit)

            ex.get_klines = flaky
            mon.breaker = None          # surface the raise (resilient seam)
            with pytest.raises(RuntimeError, match="venue down"):
                await mon.poll(force=True)
            assert bus.get("market_data_BTCUSDC") is not None
            assert bus.get("market_data_ETHUSDC") is None

        asyncio.run(go())

    def test_off_universe_symbol_rides_per_symbol_path(self):
        async def go():
            ex = _exchange(("BTCUSDC", "DOGEUSDC"))
            clock = {"t": 0.0}
            bus = EventBus()
            mon = MarketMonitor(bus, ex, symbols=["BTCUSDC"],
                                now_fn=lambda: clock["t"],
                                kline_limit=LIMIT, fused=True)
            # a stream with restrict_to_universe=False can request symbols
            # the engine has no lane for — they fall back, still publish
            assert await mon.poll(force=True,
                                  symbols=["BTCUSDC", "DOGEUSDC"]) == 2
            assert bus.get("market_data_DOGEUSDC") is not None

        asyncio.run(go())


class TestPollContract:
    """The acceptance contract: one jitted dispatch + one host readback per
    poll at S symbols × F frames, no recompiles at steady state, delta-only
    uploads.  Tier-1 so a regression fails fast, and time-budgeted."""

    def test_one_dispatch_one_sync_no_recompile(self, monkeypatch):
        # the zero-recompile assertion rides the meshprof RecompileSentinel
        # (utils/meshprof.py) — the SAME watch-window counter production
        # pages on — instead of an ad-hoc JitCompileMonitor sample
        from ai_crypto_trader_tpu.utils import meshprof

        async def go():
            symbols = ("BTCUSDC", "ETHUSDC")
            ex = _exchange(symbols)
            clock = {"t": 0.0}
            bus = EventBus()
            mon = MarketMonitor(bus, ex, symbols=list(symbols),
                                now_fn=lambda: clock["t"],
                                kline_limit=LIMIT, fused=True)
            syncs = {"n": 0}
            real_read = tick_engine.host_read

            def counting_read(tree):
                syncs["n"] += 1
                return real_read(tree)

            monkeypatch.setattr(tick_engine, "host_read", counting_read)
            mp = meshprof.MeshProf()
            with meshprof.use(mp):
                assert await mon.poll(force=True) == 2  # seed + compile
                assert syncs["n"] == 1
                eng = mon._engine
                assert eng.dispatch_count == 1
                assert eng.last_stats["full_seed"]

                ex.advance(steps=1)
                clock["t"] += 60.0
                import time as _time
                t0 = _time.perf_counter()
                assert await mon.poll() == 2            # steady state
                steady_s = _time.perf_counter() - t0
            # the sentinel attributed ZERO compiles to the steady window —
            # the production invariant (SteadyStateRecompile) verbatim
            assert mp.recompiles.steady_total() == 0, mp.recompiles.status()
            assert mp.recompiles.windows["tick_engine"] == 2
            assert mp.transfers.total() == 0           # no guarded pulls
            assert syncs["n"] == 2                     # ONE more host sync
            assert eng.dispatch_count == 2             # ONE more dispatch
            stats = eng.last_stats
            assert stats["dispatches"] == 1
            assert not stats["full_seed"]
            # delta upload: the fixed scatter list (rows + 3 index arrays),
            # independent of the window length T — never whole windows
            assert 0 < stats["upload_rows"] <= eng.max_new * stats["lanes"]
            cap = stats["lanes"] * eng.max_new * (5 * 4 + 3 * 4)
            assert stats["upload_bytes"] <= cap < eng._ring_np.nbytes
            # budget: a steady poll that recompiles takes tens of seconds;
            # this bound fails on any per-poll compile while staying far
            # above honest scheduling noise
            assert steady_s < 2.0, f"steady fused poll took {steady_s:.2f}s"

        asyncio.run(go())

    def test_tickpath_waterfall_rides_contract(self, monkeypatch):
        """ISSUE 16: with the decision critical-path observatory ACTIVE,
        the one-dispatch/one-sync contract holds verbatim — the waterfall
        is stitched from seams the poll already crosses, so it adds ZERO
        dispatches and ZERO host syncs — and the recorded engine phases
        sum to (at most) the measured poll wall: the observatory
        decomposes the latency, it never invents time."""
        from ai_crypto_trader_tpu.obs import tickpath
        from ai_crypto_trader_tpu.obs.tickpath import TickPathScope

        async def go():
            symbols = ("BTCUSDC", "ETHUSDC")
            ex = _exchange(symbols)
            clock = {"t": 0.0}
            mon = MarketMonitor(EventBus(), ex, symbols=list(symbols),
                                now_fn=lambda: clock["t"],
                                kline_limit=LIMIT, fused=True)
            syncs = {"n": 0}
            real_read = tick_engine.host_read

            def counting_read(tree):
                syncs["n"] += 1
                return real_read(tree)

            monkeypatch.setattr(tick_engine, "host_read", counting_read)
            scope = TickPathScope()
            with tickpath.use(scope):
                assert await mon.poll(force=True) == 2   # seed + compile
                ex.advance(steps=1)
                clock["t"] += 60.0
                import time as _time
                t0 = _time.perf_counter()
                assert await mon.poll() == 2             # steady state
                wall_ms = (_time.perf_counter() - t0) * 1e3
            eng = mon._engine
            assert syncs["n"] == 2            # ONE sync per poll — the
            #                                   observatory added none
            assert eng.dispatch_count == 2    # and no extra dispatches
            st = scope.status()
            engine_phases = ("scatter_build", "dispatch",
                             "device_compute", "host_read")
            for ph in engine_phases:
                assert st["phases"][ph]["count"] == 2, (ph, st)
            # the steady poll's engine slices are disjoint sub-spans of
            # the same wall clock (5% timer slack)
            sum_ms = sum(st["phases"][ph]["last_ms"]
                         for ph in engine_phases)
            assert sum_ms <= wall_ms * 1.05, (sum_ms, wall_ms)
            # the seed's cold window landed in the ledger (compiles may
            # read 0 when an earlier test already populated the process
            # jit cache — the WINDOW is the contract), and overlap
            # headroom observed on both polls
            entry = scope.cold_programs["tick_engine"]
            assert entry["wall_ms"] > 0.0 and entry["compile_ms"] >= 0.0
            assert scope.overlap.count == 2
            assert st["bottleneck"] in tickpath.PHASES

        asyncio.run(go())

    def test_ring_delta_matches_fresh_seed(self):
        """Drive the engine through incremental updates, then compare its
        outputs to a FRESH engine seeded directly on the same klines —
        pins the ring base-pointer/scatter bookkeeping."""
        symbols = ["BTCUSDC", "ETHUSDC"]
        ex = _exchange(tuple(symbols))
        frames = ("1m", "3m", "5m")
        eng = TickEngine(symbols, frames, window=LIMIT)

        def snap():
            return {(s, iv): ex.get_klines(s, iv, LIMIT)[-LIMIT:]
                    for s in symbols for iv in frames}

        for _ in range(5):
            for (s, iv), kl in snap().items():
                eng.ingest(s, iv, kl)
            out_inc = eng.step()
            ex.advance(steps=1)
        assert not eng.last_stats["full_seed"]

        fresh = TickEngine(symbols, frames, window=LIMIT)
        ex.advance(steps=0)  # same cursor
        for (s, iv), kl in {(s, iv): ex.get_klines(s, iv, LIMIT)[-LIMIT:]
                            for s in symbols for iv in frames}.items():
            fresh.ingest(s, iv, kl)
        # note: the incremental engine last stepped BEFORE the final
        # advance; re-ingest the current snapshot to align both
        for (s, iv), kl in snap().items():
            eng.ingest(s, iv, kl)
        out_inc = eng.step()
        out_fresh = fresh.step()
        for key in out_fresh:
            if key == "combo":
                for n, v in out_fresh["combo"].items():
                    np.testing.assert_allclose(
                        out_inc["combo"][n], v, rtol=1e-5, atol=1e-6,
                        err_msg=f"combo.{n}")
            else:
                np.testing.assert_allclose(
                    out_inc[key], out_fresh[key], rtol=1e-5, atol=1e-6,
                    err_msg=key)

    def test_gap_triggers_reseed_not_garbage(self):
        """A window jump larger than max_new (reconnect gap) re-seeds the
        slot instead of scattering a bounded delta."""
        symbols = ["BTCUSDC"]
        ex = _exchange(("BTCUSDC",))
        eng = TickEngine(symbols, ("1m",), window=LIMIT, max_new=4)
        eng.ingest("BTCUSDC", "1m", ex.get_klines("BTCUSDC", "1m", LIMIT))
        eng.step()
        seeds_before = eng.full_seeds
        ex.advance(steps=50)                    # >> max_new candles
        eng.ingest("BTCUSDC", "1m", ex.get_klines("BTCUSDC", "1m", LIMIT))
        out = eng.step()
        assert eng.full_seeds == seeds_before + 1
        assert eng.last_stats["full_seed"]
        c = ex.get_klines("BTCUSDC", "1m", 1)[-1][4]
        assert float(out["current_price"][0, 0]) == pytest.approx(c)


class TestBatchedPredict:
    def test_batched_matches_single_predict(self):
        """predict_prices_batched == predict_prices per lane, for models
        with distinct params/scalers sharing one architecture (the
        PredictionService grouping)."""
        import jax
        import jax.numpy as jnp

        from ai_crypto_trader_tpu.models import build_model
        from ai_crypto_trader_tpu.models.train import (
            Scaler, TrainResult, predict_prices, predict_prices_batched)

        rng = np.random.default_rng(5)
        seq_len, F = 12, 5
        results, feats = [], []
        for lane in range(3):
            model = build_model("lstm", units=4)
            series = np.cumsum(
                rng.normal(1.0, 0.1, (seq_len + 6, F)), axis=0
            ).astype(np.float32) + 10.0 * (lane + 1)
            params = model.init(jax.random.PRNGKey(lane),
                                jnp.zeros((1, seq_len, F)), False)
            scaler = Scaler(jnp.asarray(series.min(axis=0)),
                            jnp.asarray(series.max(axis=0)))
            results.append(TrainResult(
                params=params, model_type="lstm", scaler=scaler,
                model_kwargs={"units": 4}, best_val_loss=0.01 * (lane + 1),
                target_col=3))
            feats.append(series)
        batched = predict_prices_batched(results, feats, seq_len=seq_len)
        for r, f, b in zip(results, feats, batched):
            single = predict_prices(r, f, seq_len=seq_len)
            assert float(np.ravel(b["predicted_price"])[0]) == pytest.approx(
                float(np.ravel(single["predicted_price"])[0]), rel=1e-5)
            assert b["confidence"] == pytest.approx(single["confidence"])

    def test_service_groups_by_architecture(self):
        """The service's _predict_jobs runs one stacked program for an
        architecture group and per-pair programs for singletons, and
        preserves job order."""
        from ai_crypto_trader_tpu.models.service import PredictionService

        calls = []

        class FakeResult:
            def __init__(self, mt, kw):
                self.model_type = mt
                self.model_kwargs = kw

        svc = PredictionService(EventBus(), ["A", "B", "C"],
                                now_fn=lambda: 0.0)
        jobs = [("A", "1m", FakeResult("lstm", {"units": 4}), "fa"),
                ("B", "1m", FakeResult("lstm", {"units": 4}), "fb"),
                ("C", "1m", FakeResult("gru", {"units": 4}), "fc")]

        import ai_crypto_trader_tpu.models.service as service_mod

        def fake_batched(rs, fs, seq_len):
            calls.append(("batch", len(rs)))
            return [{"p": f} for f in fs]

        def fake_single(r, f, seq_len):
            calls.append(("single", f))
            return {"p": f}

        orig_b = service_mod.predict_prices_batched
        orig_s = service_mod.predict_prices
        service_mod.predict_prices_batched = fake_batched
        service_mod.predict_prices = fake_single
        try:
            preds = svc._predict_jobs(jobs)
        finally:
            service_mod.predict_prices_batched = orig_b
            service_mod.predict_prices = orig_s
        assert preds == [{"p": "fa"}, {"p": "fb"}, {"p": "fc"}]
        assert ("batch", 2) in calls
        assert ("single", "fc") in calls
