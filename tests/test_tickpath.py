"""Decision critical-path observatory (obs/tickpath.py): phase waterfall
windows + the named bottleneck (injected-delay drill), clock-skew
clamping, the event→decision age SLO and its alert input, the cold-start
ledger, the metric export literals, and the module-global on/off seam.

The drill class is the ISSUE 16 acceptance: inject a delay into EACH
pipeline stage in turn and the observatory must name exactly that stage
as the bottleneck — the waterfall is only useful if it localizes.
"""

import asyncio

import pytest

from ai_crypto_trader_tpu.obs import tickpath
from ai_crypto_trader_tpu.obs.tickpath import (PHASES, TickPathScope)
from ai_crypto_trader_tpu.utils.metrics import MetricsRegistry


@pytest.fixture(autouse=True)
def _no_ambient_scope():
    """Each test starts (and the suite ends) with the observatory off."""
    tickpath.disable()
    yield
    tickpath.disable()


class TestWaterfall:
    def test_status_covers_every_phase(self):
        """The status block always carries the FULL bounded phase set —
        a never-observed phase reads as zeros, not a missing key (a hole
        in the waterfall table would hide an uninstrumented seam)."""
        tp = TickPathScope()
        tp.observe_phase("dispatch", 0.004)
        st = tp.status()
        assert tuple(st["phases"]) == PHASES
        assert st["phases"]["dispatch"]["count"] == 1
        assert st["phases"]["dispatch"]["last_ms"] == pytest.approx(4.0)
        assert st["phases"]["parse"] == {"count": 0, "p50_ms": 0.0,
                                         "p99_ms": 0.0, "last_ms": 0.0}

    def test_bottleneck_is_largest_p99(self):
        tp = TickPathScope()
        assert tp.bottleneck() is None            # nothing observed yet
        for _ in range(10):
            tp.observe_phase("parse", 0.002)
            tp.observe_phase("host_read", 0.008)
            tp.observe_phase("dispatch", 0.003)
        assert tp.bottleneck() == "host_read"

    @pytest.mark.parametrize("phase", PHASES)
    def test_injected_delay_drill_names_each_stage(self, phase):
        """ISSUE 16 acceptance drill: delay stage X → the observatory
        must pin X as the named bottleneck, for every X."""
        tp = TickPathScope()
        tp.inject_delay(phase, 0.250)
        for _ in range(6):
            for name in PHASES:
                tp.observe_phase(name, 0.001)
        assert tp.bottleneck() == phase
        assert tp.alert_state()["tickpath_bottleneck_phase"] == phase

    def test_unknown_phase_never_competes(self):
        """A typo'd seam can record, but the bounded PHASES vocabulary
        decides the bottleneck — no label minting."""
        tp = TickPathScope()
        tp.observe_phase("dispatch", 0.002)
        tp.observe_phase("dispach_typo", 9.0)
        assert tp.bottleneck() == "dispatch"


class TestClockSkewGuard:
    def test_negative_phase_clamps_and_counts(self):
        tp = TickPathScope()
        tp.observe_phase("frame_wait", -0.5)
        assert tp.clock_skew_total == 1
        assert tp.status()["phases"]["frame_wait"]["last_ms"] == 0.0

    def test_skewed_ticker_ages_clamp_to_zero(self):
        """A venue whose clock runs AHEAD of the host stamps event times
        in our future → negative ages.  They must clamp to 0 and count
        as skew instead of poisoning the SLO quantiles."""
        tp = TickPathScope(min_samples=4)
        host_now_ms = 1_000_000.0
        for _ in range(8):                     # ticker 250 ms in the future
            event_ms = host_now_ms + 250.0
            clamped = tp.observe_event_age(host_now_ms - event_ms)
            assert clamped == 0.0
            host_now_ms += 60_000.0
        st = tp.status()["event_age_ms"]
        assert st["count"] == 8 and st["p99"] == 0.0
        assert tp.clock_skew_total == 8
        assert tp.alert_state()["tickpath_clock_skew_total"] == 8
        # the quantiles stayed clean: a later honest age dominates
        for _ in range(8):
            tp.observe_event_age(120.0)
        assert tp.status()["event_age_ms"]["p50"] >= 0.0

    def test_skew_counter_exports(self):
        m = MetricsRegistry()
        tp = TickPathScope(metrics=m)
        tp.observe_event_age(-1.0)
        assert m.counters[
            "crypto_trader_tpu_tickpath_clock_skew_total"] == 1.0


class TestEventAgeSLO:
    def test_alert_quiet_below_min_samples(self):
        """One compile-heavy cold tick is 100% of a tiny window — the
        breach input must read 0 until the window holds min_samples."""
        from ai_crypto_trader_tpu.utils.alerts import AlertManager

        tp = TickPathScope(min_samples=8)
        for _ in range(7):
            tp.observe_event_age(30_000.0)     # way over budget
        state = tp.alert_state()
        assert state["event_age_p99_ms"] == 0.0
        mgr = AlertManager(now_fn=lambda: 0.0)
        assert not [a for a in mgr.evaluate(state)
                    if a["name"] == "DecisionLatencyBudgetBreach"]
        tp.observe_event_age(30_000.0)         # window filled
        fired = mgr.evaluate(tp.alert_state())
        assert [a for a in fired
                if a["name"] == "DecisionLatencyBudgetBreach"]

    def test_budget_rides_the_state(self):
        tp = TickPathScope(event_age_budget_ms=50.0, min_samples=1)
        tp.observe_event_age(80.0)
        s = tp.alert_state()
        assert s["event_age_budget_ms"] == 50.0
        assert s["event_age_p99_ms"] > s["event_age_budget_ms"]


class TestColdStartLedger:
    def test_first_window_wins(self):
        tp = TickPathScope()
        tp.record_cold_start("tick_engine", wall_s=2.0, compile_s=1.5,
                             compiles=3)
        tp.record_cold_start("tick_engine", wall_s=9.0, compile_s=9.0,
                             compiles=9)       # late duplicate: ignored
        st = tp.coldstart_status()
        assert st["programs"]["tick_engine"]["wall_ms"] == 2000.0
        assert st["programs"]["tick_engine"]["compiles"] == 3
        assert st["total_wall_ms"] == 2000.0
        assert st["total_compile_ms"] == 1500.0

    def test_warm_and_ledgered_dispatches_get_noop(self):
        tp = TickPathScope()
        assert tp.coldstart("x", cold=False) is tickpath._NOOP_CTX
        tp.record_cold_start("x", wall_s=1.0, compile_s=0.5, compiles=1)
        assert tp.coldstart("x") is tickpath._NOOP_CTX

    def test_cold_window_attributes_a_real_compile(self):
        """The context manager samples the process-wide JitCompileMonitor
        around a genuinely cold jit dispatch and lands compile time in
        the ledger."""
        import jax
        import jax.numpy as jnp

        tp = TickPathScope()
        with tp.coldstart("ledger_probe"):
            # a shape/closure combination nothing else compiles
            jax.block_until_ready(
                jax.jit(lambda x: jnp.tanh(x) * 3.17)(jnp.ones((7, 3))))
        entry = tp.coldstart_status()["programs"]["ledger_probe"]
        assert entry["wall_ms"] > 0.0
        assert entry["compiles"] >= 1
        assert 0.0 < entry["compile_ms"] <= entry["wall_ms"] * 1.5


class TestExport:
    def test_export_literals_and_bottleneck_indicator(self):
        m = MetricsRegistry()
        tp = TickPathScope(metrics=m)
        for _ in range(4):
            tp.observe_phase("dispatch", 0.010)
            tp.observe_phase("parse", 0.001)
        tp.observe_overlap(0.002)
        tp.observe_event_age(42.0)
        tp.record_cold_start("tick_engine", wall_s=3.0, compile_s=2.0,
                             compiles=1)
        tp.export()
        g = m.gauges
        for phase in PHASES:                   # full bounded label set
            for q in ("p50", "p99"):
                assert (f'crypto_trader_tpu_tickpath_phase_seconds'
                        f'{{phase="{phase}",q="{q}"}}') in g
        assert g['crypto_trader_tpu_tickpath_bottleneck'
                 '{phase="dispatch"}'] == 1.0
        assert g['crypto_trader_tpu_tickpath_bottleneck'
                 '{phase="parse"}'] == 0.0
        assert g["crypto_trader_tpu_tickpath_overlap_headroom_seconds"] \
            == pytest.approx(0.002)
        assert g['crypto_trader_tpu_latency_p99_seconds'
                 '{slo="event_to_decision"}'] == pytest.approx(0.042)
        assert g["crypto_trader_tpu_coldstart_total_seconds"] \
            == pytest.approx(3.0)
        assert g['crypto_trader_tpu_coldstart_wall_seconds'
                 '{program="tick_engine"}'] == pytest.approx(3.0)
        # the event-age histogram feeds the slo_latency family the
        # devprof recording rules already aggregate
        assert any(k.startswith('crypto_trader_tpu_slo_latency_seconds'
                                '{slo="event_to_decision"}')
                   for k in m.histograms)


class TestModuleSeam:
    def test_disabled_helpers_are_noops(self):
        assert tickpath.active() is None
        tickpath.observe_phase("dispatch", 1.0)       # no crash, no state
        tickpath.observe_overlap(1.0)
        assert tickpath.observe_event_age(5.0) is None
        assert tickpath.coldstart("x") is tickpath._NOOP_CTX

    def test_use_restores_previous_scope(self):
        outer = tickpath.configure(TickPathScope())
        inner = TickPathScope()
        with tickpath.use(inner):
            assert tickpath.active() is inner
            tickpath.observe_phase("publish", 0.003)
        assert tickpath.active() is outer
        assert inner.status()["phases"]["publish"]["count"] == 1
        assert outer.status()["phases"]["publish"]["count"] == 0

    def test_launcher_installs_and_shutdown_clears(self):
        """Default-ON wiring: TradingSystem installs the observatory as
        the process-wide scope, feeds it from the tick loop, and its
        shutdown clears the global (no cross-test leakage)."""
        import sys as _sys

        _sys.path.insert(0, "tests")
        from test_shell import _series

        from ai_crypto_trader_tpu.shell.exchange import FakeExchange
        from ai_crypto_trader_tpu.shell.launcher import TradingSystem

        ex = FakeExchange({"BTCUSDC": _series()})
        ex.advance(steps=500)                  # full 1m window → the fused
        #                                        engine really dispatches
        system = TradingSystem(ex, ["BTCUSDC"], now_fn=lambda: 0.0)
        try:
            assert tickpath.active() is system.tickpath

            async def go():
                await system.tick()

            asyncio.run(go())
            st = system.tickpath.status()
            assert sum(p["count"] for p in st["phases"].values()) > 0
            assert "tick_engine" in \
                system.tickpath.coldstart_status()["programs"]
            # the rule-engine inputs ride the launcher's alert state
            s = system._alert_state()
            for key in ("event_age_p99_ms", "event_age_budget_ms",
                        "tickpath_bottleneck_phase"):
                assert key in s, key
            # provenance block for /state.json `build`
            assert {"process_start", "jax_version",
                    "backend"} <= set(system.build_info)
        finally:
            system.shutdown()
        assert tickpath.active() is None

    def test_opt_out_flag(self):
        import sys as _sys

        _sys.path.insert(0, "tests")
        from test_shell import _series

        from ai_crypto_trader_tpu.shell.exchange import FakeExchange
        from ai_crypto_trader_tpu.shell.launcher import TradingSystem

        ex = FakeExchange({"BTCUSDC": _series()})
        system = TradingSystem(ex, ["BTCUSDC"], now_fn=lambda: 0.0,
                               enable_tickpath=False)
        try:
            assert system.tickpath is None
            assert tickpath.active() is None
        finally:
            system.shutdown()
