"""Sequence parallelism: time-sharded scans vs the unsharded kernels.

The candle axis sharded over the virtual 8-device mesh must produce the
SAME numbers as the single-device associative-scan kernels — carry fix-up
collectives for the EMA family, halo exchange for windowed reductions
(parallel/time_shard.py; SURVEY §5.7's honest analog of context
parallelism)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ai_crypto_trader_tpu.ops import indicators as ind
from ai_crypto_trader_tpu.parallel.time_shard import (
    sharded_ema,
    sharded_first_order_recursion,
    sharded_rolling_mean,
)

# Slow tier (VERDICT r4 next#3): golden-parity / end-to-end /
# training / sharded-compile suite — deselected by the default
# run, executed via `pytest -m slow`.
pytestmark = pytest.mark.slow


T = 4096


@pytest.fixture(scope="module")
def series():
    rng = np.random.default_rng(42)
    return jnp.asarray(100.0 * np.cumprod(1 + rng.normal(0, 0.002, T)),
                       jnp.float32)


class TestFirstOrderRecursion:
    def test_matches_unsharded(self, mesh8, series):
        rng = np.random.default_rng(7)
        a = jnp.asarray(rng.uniform(0.8, 0.99, T), jnp.float32)
        b = jnp.asarray(rng.normal(0, 1, T), jnp.float32)
        want = ind.first_order_recursion(a, b)
        got = sharded_first_order_recursion(a, b, mesh8)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=1e-5)

    def test_result_is_time_sharded(self, mesh8, series):
        a = jnp.full((T,), 0.9, jnp.float32)
        b = series * 0.1
        got = sharded_first_order_recursion(a, b, mesh8)
        assert len(got.sharding.device_set) == 8


class TestShardedEma:
    @pytest.mark.parametrize("window", [12, 26, 200])
    def test_matches_ops_ema(self, mesh8, series, window):
        want = np.asarray(ind.ema(series, window))
        got = np.asarray(sharded_ema(series, window, mesh8))
        # identical warmup NaNs, matching values after
        np.testing.assert_array_equal(np.isnan(got), np.isnan(want))
        m = ~np.isnan(want)
        np.testing.assert_allclose(got[m], want[m], rtol=2e-5, atol=1e-4)

    def test_block_boundaries_seamless(self, mesh8, series):
        """The positions straddling device boundaries are where a wrong
        carry would show: check them explicitly."""
        window = 20
        want = np.asarray(ind.ema(series, window))
        got = np.asarray(sharded_ema(series, window, mesh8))
        blk = T // 8
        for edge in range(blk, T, blk):
            np.testing.assert_allclose(got[edge - 1:edge + 2],
                                       want[edge - 1:edge + 2],
                                       rtol=2e-5, atol=1e-4)


class TestShardedRollingMean:
    @pytest.mark.parametrize("window", [5, 20, 50])
    def test_matches_ops_rolling_mean(self, mesh8, series, window):
        want = np.asarray(ind.rolling_mean(series, window))
        got = np.asarray(sharded_rolling_mean(series, window, mesh8))
        np.testing.assert_array_equal(np.isnan(got), np.isnan(want))
        m = ~np.isnan(want)
        np.testing.assert_allclose(got[m], want[m], rtol=2e-5, atol=1e-3)

    def test_window_too_large_for_block_raises(self, mesh8):
        x = jnp.zeros((64,), jnp.float32)      # 8-candle blocks
        with pytest.raises(ValueError, match="halo"):
            sharded_rolling_mean(x, 10, mesh8)

    def test_window_one_identity(self, mesh8, series):
        got = np.asarray(sharded_rolling_mean(series, 1, mesh8))
        np.testing.assert_allclose(got, np.asarray(series), rtol=1e-6)

    def test_halo_spans_boundary(self, mesh8):
        """A spike in the last candle of block 0 must appear in block 1's
        first window means — proof the halo actually traveled."""
        x = jnp.zeros((T,), jnp.float32)
        blk = T // 8
        x = x.at[blk - 1].set(100.0)
        got = np.asarray(sharded_rolling_mean(x, 5, mesh8))
        np.testing.assert_allclose(got[blk], 20.0, rtol=1e-6)      # 100/5
        np.testing.assert_allclose(got[blk + 3], 20.0, rtol=1e-6)
        assert got[blk + 4] == 0.0                                 # spike out
