"""End-to-end tracing (utils/tracing.py): span propagation through the
EventBus across chained subscribers, JSONL round-trip, the disabled-by-
default hot path, JAX compile-vs-execute attribution, and the full
launcher tick producing one trace from bus publish → subscriber handling
→ model predict, served back by the dashboard's /traces endpoint."""

import asyncio
import json

import numpy as np
import pytest

from ai_crypto_trader_tpu.shell.bus import EventBus
from ai_crypto_trader_tpu.utils import tracing
from ai_crypto_trader_tpu.utils.metrics import MetricsRegistry
from ai_crypto_trader_tpu.utils.tracing import (
    JitCompileMonitor,
    Span,
    Tracer,
    read_jsonl,
)


@pytest.fixture(autouse=True)
def _no_global_tracer():
    """Tracing is process-global state: never leak it across tests."""
    yield
    tracing.disable()


class TestSpanBasics:
    def test_nesting_parents_and_injected_clock(self):
        clock = {"t": 100.0}
        tr = Tracer(service="svc", now_fn=lambda: clock["t"])
        with tracing.use(tr):
            with tracing.span("outer") as outer:
                clock["t"] += 1.0
                with tracing.span("inner") as inner:
                    clock["t"] += 0.5
        assert inner.trace_id == outer.trace_id
        assert inner.parent_id == outer.span_id
        assert outer.parent_id is None
        assert outer.duration == 1.5 and inner.duration == 0.5
        assert [s.name for s in tr.finished] == ["inner", "outer"]

    def test_error_status_and_attributes(self):
        tr = Tracer()
        with tracing.use(tr):
            with pytest.raises(ValueError):
                with tracing.span("boom", attributes={"k": 1}) as sp:
                    sp.add_event("about-to-fail", detail="x")
                    raise ValueError("nope")
        done = tr.finished[-1]
        assert done.status == "error"
        assert done.attributes["k"] == 1 and "error" in done.attributes
        assert done.events[0]["name"] == "about-to-fail"

    def test_span_duration_feeds_metrics_histogram(self):
        m = MetricsRegistry()
        tr = Tracer(metrics=m, now_fn=lambda: 1.0)
        with tracing.use(tr):
            with tracing.span("stage_a"):
                pass
        assert 'span_duration_seconds{stage="stage_a"}' in "".join(
            m.histograms.keys())


class TestDisabledDefault:
    def test_off_by_default_and_zero_allocation(self):
        assert tracing.active() is None
        # the disabled path returns the SAME pre-allocated no-op objects —
        # no per-call span/contextmanager allocation on the hot path
        c1, c2 = tracing.span("x"), tracing.span("y")
        assert c1 is c2
        with c1 as sp1:
            pass
        with tracing.span("z") as sp2:
            sp2.set_attribute("ignored", 1)
        assert sp1 is sp2
        assert tracing.inject() is None

    def test_bus_envelope_unstamped_when_disabled(self):
        async def go():
            bus = EventBus()
            q = bus.subscribe("c")
            await bus.publish("c", {"x": 1})
            env = q.get_nowait()
            assert "trace" not in env
        asyncio.run(go())


class TestBusPropagation:
    def test_three_chained_subscribers_share_one_trace(self, tmp_path):
        """market-tick shape: origin publish → svc_a → svc_b → svc_c, each
        republishing on its own channel; one trace_id, correct parent
        links, and the JSONL export round-trips."""
        path = str(tmp_path / "spans.jsonl")
        tr = Tracer(service="test", jsonl_path=path)

        async def go():
            bus = EventBus()
            qa, qb, qc = (bus.subscribe("ch1"), bus.subscribe("ch2"),
                          bus.subscribe("ch3"))
            with tracing.span("origin") as origin:
                await bus.publish("ch1", {"hop": 0})

            env_a = qa.get_nowait()
            with tracing.consumer_span(env_a, "svc_a.handle",
                                       service="svc_a"):
                await bus.publish("ch2", {"hop": 1})

            env_b = qb.get_nowait()
            with tracing.consumer_span(env_b, "svc_b.handle",
                                       service="svc_b"):
                await bus.publish("ch3", {"hop": 2})

            env_c = qc.get_nowait()
            with tracing.consumer_span(env_c, "svc_c.handle",
                                       service="svc_c"):
                pass
            return origin

        with tracing.use(tr):
            origin = asyncio.run(go())

        spans = {s.name: s for s in tr.finished}
        assert len(spans) == 4
        # one trace end to end
        assert {s.trace_id for s in spans.values()} == {origin.trace_id}
        # causal chain: each hop parents to the publisher's span
        assert spans["svc_a.handle"].parent_id == spans["origin"].span_id
        assert spans["svc_b.handle"].parent_id == spans["svc_a.handle"].span_id
        assert spans["svc_c.handle"].parent_id == spans["svc_b.handle"].span_id
        # JSONL export round-trips to the same spans
        loaded = {s.span_id: s for s in read_jsonl(path)}
        assert len(loaded) == 4
        for s in spans.values():
            r = loaded[s.span_id]
            assert (r.name, r.trace_id, r.parent_id, r.service) == \
                   (s.name, s.trace_id, s.parent_id, s.service)
            assert r.start == s.start and r.end == s.end

    def test_traces_view_groups_by_trace_id(self):
        tr = Tracer(now_fn=lambda: 5.0)
        with tracing.use(tr):
            with tracing.span("t1_root"):
                with tracing.span("t1_child"):
                    pass
            with tracing.span("t2_root"):
                pass
        traces = tr.traces()
        assert len(traces) == 2
        assert traces[0]["root"] == "t2_root"        # newest first
        assert traces[1]["root"] == "t1_root"
        assert traces[1]["n_spans"] == 2

    def test_slow_subscriber_drop_logged_with_trace_id(self, tmp_path):
        log_path = str(tmp_path / "log.jsonl")
        from ai_crypto_trader_tpu.utils.structlog import StructuredLogger

        tr = Tracer()

        async def go():
            bus = EventBus(max_queue=2,
                           log=StructuredLogger("bus", path=log_path))
            bus.subscribe("c")
            with tracing.span("pub") as sp:
                for i in range(4):
                    await bus.publish("c", i)
            return sp

        with tracing.use(tr):
            sp = asyncio.run(go())
        rows = [json.loads(line) for line in open(log_path)]
        drops = [r for r in rows if "slow subscriber" in r["msg"]]
        assert drops and drops[0]["channel"] == "c"
        assert drops[0]["trace_id"] == sp.trace_id
        assert drops[0]["level"] == "warning"


class TestJitCompileMonitor:
    def test_compile_attribution_first_call_only(self):
        import jax
        import jax.numpy as jnp

        mon = JitCompileMonitor.install()
        f = jax.jit(lambda x: x * 3.0 + jnp.sin(x))
        x = jnp.arange(17, dtype=jnp.float32)      # unique shape → compile
        before = mon.sample()
        jax.block_until_ready(f(x))
        first = mon.since(before)
        assert first["compiles"] >= 1 and first["compile_s"] > 0.0
        before = mon.sample()
        jax.block_until_ready(f(x))                # cached: no new compile
        second = mon.since(before)
        assert second["compiles"] == 0 and second["compile_s"] == 0.0

    def test_backtest_entry_records_breakdown(self):
        from ai_crypto_trader_tpu.backtest import prepare_inputs, run_backtest
        from ai_crypto_trader_tpu.ops import compute_indicators
        from ai_crypto_trader_tpu.data.synthetic import generate_ohlcv
        import jax.numpy as jnp

        d = generate_ohlcv(n=300, seed=1)
        arrays = {k: jnp.asarray(d[k]) for k in
                  ("open", "high", "low", "close", "volume")}
        inp = prepare_inputs(compute_indicators(arrays))
        tr = Tracer()
        with tracing.use(tr):
            run_backtest(inp)
        spans = [s for s in tr.finished if s.name == "backtest.run"]
        assert spans, [s.name for s in tr.finished]
        attrs = spans[-1].attributes
        assert attrs["candles"] == 300
        assert "total_s" in attrs and "compile_s" in attrs \
               and "execute_s" in attrs


class TestLauncherEndToEnd:
    def test_one_trace_publish_to_predict_and_traces_endpoint(self, tmp_path):
        """The acceptance path: a monitor run with tracing enabled yields a
        JSONL trace where ONE trace_id spans bus publish → analyzer
        handling → model predict (with compile-vs-execute attributes on the
        model span), and /traces serves the same trace."""
        import urllib.request

        from ai_crypto_trader_tpu.data.ingest import from_dict
        from ai_crypto_trader_tpu.data.synthetic import generate_ohlcv
        from ai_crypto_trader_tpu.models.service import PredictionService
        from ai_crypto_trader_tpu.shell.dashboard_server import DashboardServer
        from ai_crypto_trader_tpu.shell.exchange import FakeExchange
        from ai_crypto_trader_tpu.shell.launcher import TradingSystem

        jsonl = str(tmp_path / "trace.jsonl")
        clock = {"t": 1_000_000.0}
        d = generate_ohlcv(n=900, seed=3)
        series = from_dict({k: v for k, v in d.items() if k != "regime"},
                           symbol="BTCUSDC")
        ex = FakeExchange({"BTCUSDC": series}, quote_balance=10_000)
        ex.advance("BTCUSDC", steps=600)
        system = TradingSystem(ex, ["BTCUSDC"], now_fn=lambda: clock["t"],
                               enable_tracing=True, trace_jsonl=jsonl)
        system.monitor.intervals = ("1m",)         # keep the test lean
        nn = PredictionService(system.bus, ["BTCUSDC"], intervals=("1m",),
                               now_fn=lambda: clock["t"], seq_len=8,
                               epochs=1, units=4)
        system.extra_services.append(nn)
        try:
            async def go():
                for _ in range(2):
                    ex.advance("BTCUSDC")
                    clock["t"] += 60.0
                    await system.tick()
            asyncio.run(go())

            spans = read_jsonl(jsonl)
            by_name = {}
            for s in spans:
                by_name.setdefault(s.name, []).append(s)
            assert nn.predict_count >= 1
            # every stage of the pipeline produced spans
            for name in ("tick", "monitor.poll", "monitor.fetch",
                         "analyzer.handle_update", "model.train",
                         "model.predict"):
                assert name in by_name, (name, sorted(by_name))
            # ONE trace covers publish → handling → predict: the analyzer
            # span parents to the monitor.poll span that published, and the
            # model span shares the same tick-rooted trace
            analyzer = by_name["analyzer.handle_update"][0]
            poll = {s.span_id: s for s in by_name["monitor.poll"]}
            assert analyzer.parent_id in poll
            assert analyzer.trace_id == poll[analyzer.parent_id].trace_id
            predict = by_name["model.predict"][0]
            tick_traces = {s.trace_id for s in by_name["tick"]}
            assert predict.trace_id in tick_traces
            assert analyzer.trace_id in tick_traces
            # compile-vs-execute breakdown on the model span
            for key in ("total_s", "compile_s", "execute_s",
                        "cache_hits", "cache_misses"):
                assert key in predict.attributes, predict.attributes
            # logs carry the same correlation id convention
            server = DashboardServer(system, port=0).start()
            try:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{server.port}/traces",
                        timeout=5) as r:
                    served = json.loads(r.read().decode())
            finally:
                server.stop()
            assert served, "no traces served"
            served_ids = {t["trace_id"] for t in served}
            assert predict.trace_id in served_ids
            stage_names = {s["name"] for t in served for s in t["spans"]}
            assert "model.predict" in stage_names
            # and the registry carries the new histograms
            expo = system.metrics.exposition()
            assert "span_duration_seconds" in expo
            assert "jit_compile_seconds" in expo
            assert "bus_fanout_latency_seconds" in expo
            assert "bus_queue_depth" in expo
        finally:
            system.shutdown()
        # shutdown deactivates THIS system's tracer and closes the JSONL
        assert tracing.active() is None
        assert system.tracer._fh is None

    def test_tracing_off_no_spans_no_envelope_overhead(self):
        from ai_crypto_trader_tpu.data.ingest import from_dict
        from ai_crypto_trader_tpu.data.synthetic import generate_ohlcv
        from ai_crypto_trader_tpu.shell.exchange import FakeExchange
        from ai_crypto_trader_tpu.shell.launcher import TradingSystem

        clock = {"t": 1_000_000.0}
        d = generate_ohlcv(n=900, seed=3)
        series = from_dict({k: v for k, v in d.items() if k != "regime"},
                           symbol="BTCUSDC")
        ex = FakeExchange({"BTCUSDC": series}, quote_balance=10_000)
        ex.advance("BTCUSDC", steps=600)
        system = TradingSystem(ex, ["BTCUSDC"], now_fn=lambda: clock["t"])
        system.monitor.intervals = ("1m",)
        assert system.tracer is None and tracing.active() is None
        q = system.bus.subscribe("market_updates")

        async def go():
            ex.advance("BTCUSDC")
            clock["t"] += 60.0
            await system.tick()
        asyncio.run(go())
        env = q.get_nowait()
        assert "trace" not in env
