"""Compiled-epoch trainer (models/train_loop.py), fused LSTM custom-VJP
(models/fused_lstm.py), and the PR's satellite fixes: donation actually
enabled, exactly one host sync per epoch, loss-trajectory parity with the
legacy per-batch loop, the RL multi-iteration scan, pattern-recognizer
sourcing in the full stack, news poll/dedup, and the XLA-cache lock
reclaim race."""

import asyncio
import os
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

KEY = jax.random.PRNGKey(0)


def _features(n=160, f=4, seed=0):
    rng = np.random.default_rng(seed)
    t = np.arange(n)
    base = 100 + 10 * np.sin(t / 20) + rng.normal(0, 0.5, n)
    cols = [base] + [rng.normal(0, 1, n) for _ in range(f - 1)]
    return np.stack(cols, axis=1).astype(np.float32)


class TestFusedLSTM:
    """The fused layer must compute the SAME function (and gradients) as
    the textbook split/sigmoid LSTM cell it replaced."""

    @staticmethod
    def _reference_scan(zx, wh):
        T, B, H4 = zx.shape
        H = H4 // 4

        def step(carry, z):
            c, h = carry
            g = z + h @ wh
            i, f, gg, o = jnp.split(g, 4, axis=-1)
            c = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(gg)
            h = jax.nn.sigmoid(o) * jnp.tanh(c)
            return (c, h), h

        init = (jnp.zeros((B, H)), jnp.zeros((B, H)))
        return jax.lax.scan(step, init, zx)[1]

    def test_forward_and_gradient_parity(self):
        from ai_crypto_trader_tpu.models.fused_lstm import lstm_scan

        rng = np.random.default_rng(0)
        zx = jnp.asarray(rng.normal(size=(7, 3, 32)).astype(np.float32))
        wh = jnp.asarray(rng.normal(size=(8, 32)).astype(np.float32) * 0.3)

        f_fused = lambda zx, wh: jnp.sum(jnp.sin(lstm_scan(zx, wh)))
        f_ref = lambda zx, wh: jnp.sum(jnp.sin(self._reference_scan(zx, wh)))
        np.testing.assert_allclose(np.asarray(f_fused(zx, wh)),
                                   np.asarray(f_ref(zx, wh)), rtol=1e-5)
        g_fused = jax.grad(f_fused, argnums=(0, 1))(zx, wh)
        g_ref = jax.grad(f_ref, argnums=(0, 1))(zx, wh)
        for a, b in zip(g_fused, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=1e-5)


class TestCompiledEpoch:
    def test_loss_trajectory_parity_with_legacy_loop(self):
        """Same key → same per-epoch train/val losses, LR schedule, and
        early-stop point as the per-batch dispatch loop it replaced."""
        from ai_crypto_trader_tpu.models import train_model

        f = _features(160)
        kw = dict(seq_len=8, units=8, epochs=5, batch_size=32,
                  reduce_lr_patience=1, early_stopping_patience=5)
        r_new = train_model(KEY, f, "lstm", **kw)
        r_old = train_model(KEY, f, "lstm", compiled_epoch=False, **kw)

        assert r_new.epochs_run == r_old.epochs_run
        for h_new, h_old in zip(r_new.history, r_old.history):
            np.testing.assert_allclose(h_new["loss"], h_old["loss"],
                                       rtol=1e-4, atol=1e-6)
            np.testing.assert_allclose(h_new["val_loss"], h_old["val_loss"],
                                       rtol=1e-4, atol=1e-6)
            assert h_new["lr"] == h_old["lr"]
        np.testing.assert_allclose(r_new.best_val_loss, r_old.best_val_loss,
                                   rtol=1e-4)

    def test_exactly_one_host_sync_per_epoch(self, monkeypatch):
        """The loop's only device→host readback is train_loop.host_read —
        one call per epoch, metrics vector [train_loss, val_loss]."""
        from ai_crypto_trader_tpu.models import train_model
        from ai_crypto_trader_tpu.models import train_loop

        calls = []
        real = train_loop.host_read
        monkeypatch.setattr(train_loop, "host_read",
                            lambda x: calls.append(1) or real(x))
        r = train_model(KEY, _features(120), "lstm", seq_len=8, units=8,
                        epochs=3, batch_size=32, early_stopping_patience=10)
        assert len(calls) == r.epochs_run == 3

    def test_donation_enabled_no_unused_buffer_warnings(self):
        """donate_argnums must actually alias params/opt_state: the donated
        input buffers are invalidated, and XLA emits no 'donated buffer
        was not usable' warning on the steady-state call."""
        from ai_crypto_trader_tpu.models.train_loop import EpochTrainer

        w = jnp.asarray(np.random.default_rng(0).normal(size=(4, 1)),
                        jnp.float32)
        params = {"w": w}
        tx = optax.adam(1e-2)
        opt_state = tx.init(params)
        X = jnp.asarray(np.random.default_rng(1).normal(size=(64, 4)),
                        jnp.float32)
        y = X @ w + 0.1

        trainer = EpochTrainer(
            lambda p, xb, yb, rng: jnp.mean((xb @ p["w"] - yb) ** 2), tx)
        params, opt_state, _ = trainer.epoch(       # compile call
            params, opt_state, X, y, KEY, KEY, batch_size=16)
        donated_leaf = params["w"]
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            params, opt_state, m = trainer.epoch(
                params, opt_state, X, y, KEY, KEY, batch_size=16)
            float(m[0])
        assert donated_leaf.is_deleted()            # buffer really donated
        assert not [w_ for w_ in caught
                    if "donated" in str(w_.message).lower()]
        assert not params["w"].is_deleted()

    def test_bf16_precision_smoke(self):
        from ai_crypto_trader_tpu.models import train_model

        r = train_model(KEY, _features(120), "lstm", seq_len=8, units=8,
                        epochs=2, batch_size=32, precision="bf16")
        assert np.isfinite([h["loss"] for h in r.history]).all()
        assert np.isfinite(r.best_val_loss)

    def test_unknown_precision_rejected(self):
        from ai_crypto_trader_tpu.models.train_loop import canonical_precision

        with pytest.raises(ValueError):
            canonical_precision("f16")
        assert canonical_precision("bf16") == "bfloat16"
        # "f32" must force FULL float32 (on TPU the backend default is the
        # MXU's bf16-ish DEFAULT — None would silently keep it)
        assert canonical_precision("f32") == "float32"
        assert canonical_precision(None) is None


class TestPatternTrainingCompiled:
    def test_loss_decreases_and_trained_flag(self):
        from ai_crypto_trader_tpu.patterns.model import train_pattern_model

        rec = train_pattern_model(KEY, "cnn", n_per_class=8, epochs=3,
                                  T=24, batch_size=32)
        losses = [h["loss"] for h in rec.history]
        assert len(losses) == 3 and np.isfinite(losses).all()
        assert losses[-1] < losses[0]
        assert rec.trained is True


@pytest.mark.slow
class TestRLMultiIterationScan:
    def test_matches_per_iteration_loop(self):
        from ai_crypto_trader_tpu.rl import (
            DQNConfig, dqn_init, make_env_params, train_iteration,
            train_iterations)

        rng = np.random.default_rng(0)
        ind = {k: jnp.asarray(rng.normal(50, 10, 256).astype(np.float32))
               for k in ("close", "rsi", "macd", "bb_position", "stoch_k",
                         "atr", "volume", "williams_r", "signal", "ema_12",
                         "ema_26", "sma_20")}
        p = make_env_params(ind, episode_len=32)
        cfg = DQNConfig(num_envs=4, replay_capacity=256, batch_size=8,
                        rollout_len=2, learn_steps_per_iter=1)

        st_loop = dqn_init(KEY, p, cfg)
        for _ in range(3):
            st_loop, m_loop = train_iteration(p, st_loop, cfg)

        st_scan = dqn_init(KEY, p, cfg)
        st_scan, m_scan = train_iterations(p, st_scan, cfg, n_iters=3)

        np.testing.assert_allclose(
            np.asarray(st_loop.params["params"]["Dense_0"]["kernel"]),
            np.asarray(st_scan.params["params"]["Dense_0"]["kernel"]),
            rtol=1e-5, atol=1e-6)
        assert m_scan["loss"].shape == (3,)
        np.testing.assert_allclose(float(m_loop["loss"]),
                                   float(m_scan["loss"][-1]), rtol=1e-5)

    def test_train_dqn_history_selection_unchanged(self):
        from ai_crypto_trader_tpu.rl import (
            DQNConfig, make_env_params, train_dqn)

        rng = np.random.default_rng(0)
        ind = {k: jnp.asarray(rng.normal(50, 10, 256).astype(np.float32))
               for k in ("close", "rsi", "macd", "bb_position", "stoch_k",
                         "atr", "volume", "williams_r", "signal", "ema_12",
                         "ema_26", "sma_20")}
        p = make_env_params(ind, episode_len=32)
        cfg = DQNConfig(num_envs=4, replay_capacity=256, batch_size=8,
                        rollout_len=2, learn_steps_per_iter=1)
        _, hist = train_dqn(KEY, p, cfg, iterations=5, log_every=2)
        assert [h["iter"] for h in hist] == [0, 2, 4]
        assert all(np.isfinite(h["loss"]) for h in hist)


class TestStackPatternSources:
    def test_checkpoint_roundtrip_and_untrained_fallback(self, tmp_path):
        from ai_crypto_trader_tpu.patterns.model import train_pattern_model
        from ai_crypto_trader_tpu.shell.stack import _pattern_recognizer
        from ai_crypto_trader_tpu.utils.checkpoint import save_checkpoint

        ckpt = str(tmp_path / "pattern_cnn")
        rec = train_pattern_model(KEY, "cnn", n_per_class=4, epochs=1, T=24)
        save_checkpoint(ckpt, rec.params, metadata={"model_type": "cnn"})

        loaded = _pattern_recognizer(24, {"checkpoint": ckpt})
        assert loaded.trained is True
        np.testing.assert_allclose(
            np.asarray(jax.tree.leaves(loaded.params)[0]),
            np.asarray(jax.tree.leaves(rec.params)[0]))

        fallback = _pattern_recognizer(
            24, {"checkpoint": None, "train_on_start": False})
        assert fallback.trained is False
        assert fallback.params is not None

        # an incompatible checkpoint (different seq_len → different flatten
        # width) must fall through, not crash detect-time
        mismatched = _pattern_recognizer(
            48, {"checkpoint": ckpt, "train_on_start": False})
        assert mismatched.trained is False

    def test_startup_training_persists_checkpoint(self, tmp_path):
        from ai_crypto_trader_tpu.shell.stack import _pattern_recognizer

        ckpt = str(tmp_path / "pattern_cnn")
        rec = _pattern_recognizer(
            24, {"checkpoint": ckpt,
                 "train_kwargs": {"epochs": 1, "n_per_class": 4}})
        assert rec.trained is True and rec.history
        assert os.path.isdir(ckpt)          # persisted for the next start
        again = _pattern_recognizer(24, {"checkpoint": ckpt})
        assert again.trained is True and not again.history  # loaded, not re-trained

    def test_untrained_recognizer_tags_published_signals(self):
        from ai_crypto_trader_tpu.patterns.service import ChartPatternService
        from ai_crypto_trader_tpu.shell.bus import EventBus
        from ai_crypto_trader_tpu.shell.stack import _pattern_recognizer

        rec = _pattern_recognizer(
            24, {"checkpoint": None, "train_on_start": False})
        bus = EventBus()
        rng = np.random.default_rng(0)
        base = 100 + np.cumsum(rng.normal(0, 0.3, 80))
        klines = [[i * 60_000.0, c, c + 0.5, c - 0.5, c + 0.1, 10.0]
                  for i, c in enumerate(base)]
        bus.set("historical_data_BTCUSDC_1m", klines)
        svc = ChartPatternService(bus, rec, ["BTCUSDC"], seq_len=24,
                                  confidence_threshold=0.0,
                                  min_publish_strength=0.0,
                                  now_fn=lambda: 1000.0)
        asyncio.run(svc.run_once())
        analysis = bus.get("pattern_analysis_BTCUSDC")
        assert analysis["model_status"] == "untrained"
        signals = bus.get("pattern_signals_BTCUSDC")
        if signals is not None:             # published only when non-neutral
            assert signals["model_status"] == "untrained"


class TestNewsSatellites:
    def _service(self, provider, now):
        from ai_crypto_trader_tpu.shell.bus import EventBus
        from ai_crypto_trader_tpu.social.news import NewsService

        bus = EventBus()
        return NewsService(bus, ["BTCUSDC"], provider=provider,
                           poll_interval_s=600.0,
                           now_fn=lambda: now["t"]), bus

    def test_empty_fetch_respects_poll_interval(self):
        calls = []
        now = {"t": 0.0}
        svc, _ = self._service(
            lambda bus, symbol: calls.append(symbol) or [], now)
        asyncio.run(svc.run_once())
        assert len(calls) == 1
        now["t"] = 100.0                    # inside the 600 s interval
        asyncio.run(svc.run_once())
        assert len(calls) == 1              # empty fetch burned the slot
        now["t"] = 700.0
        asyncio.run(svc.run_once())
        assert len(calls) == 2

    def test_recent_feed_dedups_repeated_headline(self):
        article = {"title": "BTC steady", "body": "BTC (BTC) moved 0.0%.",
                   "published_at": 42.0, "source": "wire"}
        now = {"t": 0.0}
        svc, bus = self._service(lambda bus, symbol: [dict(article)], now)
        asyncio.run(svc.run_once())
        now["t"] = 700.0                    # provider re-serves the headline
        asyncio.run(svc.run_once())
        recent = bus.get("news_recent_BTCUSDC")
        assert len(recent) == 1
        assert recent[0]["title"] == "BTC steady"
        # a genuinely new headline still appends
        article["title"] = "BTC breaks out"
        now["t"] = 1400.0
        asyncio.run(svc.run_once())
        assert [e["title"] for e in bus.get("news_recent_BTCUSDC")] == \
            ["BTC steady", "BTC breaks out"]

    def test_recent_feed_dedups_reserved_batches(self):
        """A provider that re-serves a BATCH of headlines must not grow the
        feed — tail-only comparison would re-append every entry but one."""
        batch = [{"title": t, "body": f"{t}.", "published_at": i,
                  "source": "wire"} for i, t in enumerate(["A", "B", "C"])]
        now = {"t": 0.0}
        svc, bus = self._service(
            lambda bus, symbol: [dict(a) for a in batch], now)
        asyncio.run(svc.run_once())
        now["t"] = 700.0
        asyncio.run(svc.run_once())
        assert [e["title"] for e in bus.get("news_recent_BTCUSDC")] == \
            ["A", "B", "C"]

    def test_dedup_without_published_at_keys_on_title(self):
        """published_at is optional; the stored field defaults to poll time,
        so re-served timestamp-less headlines must dedup on title alone."""
        article = {"title": "BTC steady", "body": "BTC (BTC) moved.",
                   "source": "wire"}          # no published_at
        now = {"t": 0.0}
        svc, bus = self._service(lambda bus, symbol: [dict(article)], now)
        asyncio.run(svc.run_once())
        now["t"] = 700.0
        asyncio.run(svc.run_once())
        assert len(bus.get("news_recent_BTCUSDC")) == 1


class TestCacheLockReclaim:
    """flock-based writer lock: dead owners release automatically (the
    kernel drops the lock with the fd), live owners exclude atomically —
    no stale-pidfile reclaim step left to race on."""

    def test_stale_pidfile_is_not_a_lock(self, tmp_path):
        import conftest

        session_fh = conftest._CACHE_LOCK_FH    # don't disturb the session's lock
        cache_dir = str(tmp_path / "cache")
        lock = os.path.join(cache_dir, ".writer.pid")
        os.makedirs(cache_dir)
        with open(lock, "w") as f:
            f.write("999999999")            # dead owner's breadcrumb, no flock
        try:
            assert conftest._acquire_cache_lock(cache_dir) is True
            with open(lock) as f:
                assert int(f.read()) == os.getpid()
        finally:
            conftest._CACHE_LOCK_FH.close()
            conftest._CACHE_LOCK_FH = session_fh

    def test_held_lock_excludes_second_acquirer(self, tmp_path):
        import fcntl

        import conftest

        cache_dir = str(tmp_path / "cache")
        lock = os.path.join(cache_dir, ".writer.pid")
        os.makedirs(cache_dir)
        holder = open(lock, "a+")           # a concurrent run's open fd
        fcntl.flock(holder, fcntl.LOCK_EX | fcntl.LOCK_NB)
        try:
            assert conftest._acquire_cache_lock(cache_dir) is False
        finally:
            holder.close()
