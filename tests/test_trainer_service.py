"""Continuous PBT training service (ISSUE 20): crash-safe generation
checkpoints, in-program member quarantine, last-good rolling
recalibration.

Tier-1 on the tiny LOB scenario env (4 scenarios x 64 steps, 8-member
fleets, 8-unit nets) so the whole file compiles in seconds:

  * alert vocabulary: `TrainingFleetStalled` / `MemberQuarantined` exist
    with coherent predicates in BOTH rule engines (utils/alerts.py and
    monitoring/alert_rules.yml);
  * the checkpoint codec: a `checkpoint_payload` JSON round trip
    restores the FULL vmapped fleet BIT-exactly; population drift,
    config drift, format drift, leaf-shape drift and per-array bit rot
    all refuse loudly; torn tails fall back to the previous intact
    record; compaction keeps a 50-generation journal O(one snapshot);
  * THE resume-parity pin: a service killed after a torn checkpoint
    append resumes from the newest intact record and produces
    BIT-identical fitness history, lineage and final state to an
    uninterrupted same-seed run — and a service ticking one generation
    at a time is bit-interchangeable with one `train_pbt` call;
  * containment: a poisoned mid-pack member trips the in-program
    quarantine while every healthy member stays BIT-identical to a
    clean twin fleet (P=8 tier-1, P=64 in the slow tier); the heal IS
    PBT's own forced-exploit clone (pinned against a plain exploit of
    the same survivor under the same key); trip/heal never recompiles
    (the meshprof sentinel stays green);
  * the service rim: cadence gating, rolling recalibration with
    last-good fallback on poisoned capture windows (a recalibration is
    a TRANSFER, never a recompile), gauges/alert inputs/status block,
    launcher supervision via `attach_trainer` (StageBreaker + heartbeat
    + crash-loop isolation), `cli rl --resume` provenance;
  * the chaos soak (tier-1 smoke; `-m slow` long run): kills + a
    poisoned member + a poisoned recalibration window in one lifetime,
    ending healthy with a winner through the adoption gate, the verdict
    journaled, blast radius == the faulted member, zero steady-state
    recompiles.
"""

import asyncio
import json
import os
import sys

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from ai_crypto_trader_tpu.rl import (
    DQNConfig,
    PBTConfig,
    obs_size,
    pbt_env_params,
)
from ai_crypto_trader_tpu.rl.population import (
    _exchange_program,
    _program_pcfg,
    pop_init,
    train_pbt,
)
from ai_crypto_trader_tpu.rl.trainer_service import (
    PBT_CHECKPOINT_KIND,
    PBTTrainerService,
    checkpoint_payload,
    load_checkpoint,
    restore_checkpoint,
)
from ai_crypto_trader_tpu.testing import chaos
from ai_crypto_trader_tpu.utils import meshprof
from ai_crypto_trader_tpu.utils.journal import SnapshotJournal, replay
from ai_crypto_trader_tpu.utils.metrics import MetricsRegistry

KEY = jax.random.PRNGKey(0)

# tiny everywhere: the contracts are structural, not statistical
PCFG = PBTConfig(population=8, generations=3, iters_per_generation=2,
                 eval_steps=4)


@pytest.fixture(scope="module")
def env():
    params, _labels = pbt_env_params(jax.random.PRNGKey(7), num_scenarios=4,
                                     steps=64, episode_len=32,
                                     dynamics="lob")
    return params


@pytest.fixture(scope="module")
def cfg(env):
    return DQNConfig(state_size=obs_size(env), num_envs=2, rollout_len=2,
                     hidden=(8,), replay_capacity=64, batch_size=8,
                     learn_steps_per_iter=1, target_sync_every=3)


def _leaves_equal(tree_a, tree_b):
    la, lb = jax.tree.leaves(tree_a), jax.tree.leaves(tree_b)
    assert len(la) == len(lb)
    return all(np.array_equal(np.asarray(a), np.asarray(b))
               for a, b in zip(la, lb))


def _good_records(n=6, seed=0):
    """A healthy synthetic capture window `fit_flow_params` accepts."""
    rng = np.random.default_rng(seed)
    recs = []
    for i in range(n):
        bids = [[100.0 - 0.5 * j, 2.0 + rng.uniform(0, 0.5)]
                for j in range(4)]
        asks = [[100.5 + 0.5 * j, 2.0 + rng.uniform(0, 0.5)]
                for j in range(4)]
        recs.append({"symbol": "BTCUSDC", "kind": "snapshot",
                     "E": 1_700_000_000_000 + i * 1000,
                     "U": i * 10, "u": i * 10 + 9,
                     "bids": bids, "asks": asks})
    return recs


def _mid_member(env, cfg, generations=1):
    """A mid-pack member index by CLEAN gen-0 fitness — poisoning it
    keeps both exchange brackets unchanged among healthy members, the
    premise of the bit-identity containment pin.  The rank must come
    from a STABLE sort (what `quantile_split`'s jnp.argsort uses): with
    fitness ties spanning a bracket boundary, an unstable sort can call
    a top-bracket donor "mid-pack"."""
    clean = train_pbt(KEY, env, cfg, PCFG._replace(generations=generations))
    order = np.argsort(np.array(clean.history[0]["fitness"]), kind="stable")
    return clean, int(order[len(order) // 2])


def _service(env, cfg, **kw):
    kw.setdefault("now_fn", lambda: 1000.0)
    return PBTTrainerService(cfg=cfg, pcfg=PCFG._replace(generations=1),
                             env_params=env, seed=0, **kw)


def _tick(svc):
    return asyncio.run(svc.run_once())


@pytest.fixture(autouse=True)
def no_persistent_compile_cache():
    """This module runs with the persistent compile cache OFF.  Its
    fleet programs produce the suite's biggest cache entries, and it
    sits at the end of the alphabetical run order — the tests most
    likely to be straddling a write when a timeout kills the run, and a
    torn entry segfaults jax on read-back (the hazard conftest
    documents).  Nothing here needs the on-disk cache: every pin is
    bit-parity or a recompile count, and the big programs compile once
    per run then hit the in-memory jit cache across tests."""
    prev = jax.config.jax_compilation_cache_dir
    jax.config.update("jax_compilation_cache_dir", None)
    yield
    jax.config.update("jax_compilation_cache_dir", prev)


# --------------------------------------------------------------------------
# alert vocabulary: both rule engines, coherent predicates
# --------------------------------------------------------------------------

class TestTrainerVocabulary:
    def test_alert_rules_exist_in_both_engines(self):
        from ai_crypto_trader_tpu.utils.alerts import default_rules

        rules = {r.name: r for r in default_rules()}

        quarantined = rules["MemberQuarantined"]
        assert quarantined.severity == "warning"
        assert quarantined.predicate({"pbt_quarantined_members": 1})
        assert not quarantined.predicate({"pbt_quarantined_members": 0})
        assert not quarantined.predicate({})

        stalled = rules["TrainingFleetStalled"]
        assert stalled.severity == "warning"
        assert stalled.predicate({"pbt_generation_age_s": 301.0,
                                  "pbt_stall_after_s": 300.0})
        assert not stalled.predicate({"pbt_generation_age_s": 299.0,
                                      "pbt_stall_after_s": 300.0})
        # no trainer attached -> no stall threshold -> never fires
        assert not stalled.predicate({"pbt_generation_age_s": 1e9})
        assert not stalled.predicate({})

        with open(os.path.join(os.path.dirname(__file__), "..",
                               "monitoring", "alert_rules.yml"),
                  encoding="utf-8") as f:
            yml = f.read()
        assert "TrainingFleetStalled" in yml
        assert "MemberQuarantined" in yml
        assert "crypto_trader_tpu_pbt_quarantined_members > 0" in yml
        assert "crypto_trader_tpu_pbt_last_generation_timestamp" in yml
        assert "crypto_trader_tpu_pbt_generation_interval_seconds" in yml


# --------------------------------------------------------------------------
# the checkpoint codec: bit-exact restore, loud refusal on every drift axis
# --------------------------------------------------------------------------

class TestCheckpointCodec:
    @pytest.fixture(scope="class")
    def pop(self, env, cfg):
        return pop_init(KEY, env, cfg, PCFG)

    def _payload(self, pop, cfg, generation=3):
        return checkpoint_payload(pop, generation=generation, cfg=cfg,
                                  pcfg=PCFG, seed=0,
                                  history=[{"generation": 0}])

    def test_json_roundtrip_restores_bit_exact(self, env, cfg, pop):
        payload = json.loads(json.dumps(self._payload(pop, cfg)))
        restored = restore_checkpoint(payload, cfg, PCFG, env)
        assert _leaves_equal(restored, pop)
        # the quarantine bits and cooldowns ride the same snapshot
        assert restored.quarantined.dtype == jnp.bool_
        assert payload["generation"] == 3

    def test_population_drift_rejected(self, env, cfg, pop):
        payload = self._payload(pop, cfg)
        with pytest.raises(ValueError, match="refusing to load a drifted"):
            restore_checkpoint(payload, cfg, PCFG._replace(population=4),
                               env)

    def test_cfg_drift_rejected_naming_the_keys(self, env, cfg, pop):
        payload = self._payload(pop, cfg)
        with pytest.raises(ValueError, match="training-config drift.*hidden"):
            restore_checkpoint(payload, cfg._replace(hidden=(16,)), PCFG,
                               env)

    def test_format_drift_rejected(self, env, cfg, pop):
        payload = dict(self._payload(pop, cfg), format=99)
        with pytest.raises(ValueError, match="refusing to guess a layout"):
            restore_checkpoint(payload, cfg, PCFG, env)

    def test_array_bit_rot_raises(self, env, cfg, pop):
        payload = self._payload(pop, cfg)
        rec = dict(payload["arrays"][0])
        data = rec["data"]
        rec["data"] = ("B" if data[0] != "B" else "C") + data[1:]
        payload["arrays"] = [rec] + payload["arrays"][1:]
        with pytest.raises(ValueError, match="crc mismatch"):
            restore_checkpoint(payload, cfg, PCFG, env)

    def test_torn_tail_falls_back_to_previous_intact(self, env, cfg, pop,
                                                     tmp_path):
        path = str(tmp_path / "pbt.journal")
        journal = SnapshotJournal(path, kind=PBT_CHECKPOINT_KIND)
        journal.write(self._payload(pop, cfg, generation=1))
        journal.write(self._payload(pop, cfg, generation=2))
        journal.close()
        chaos.torn_tail(path, keep_bytes=41)
        payload, stats = load_checkpoint(path)
        assert stats["torn_tail"] is True
        assert payload is not None and payload["generation"] == 1
        assert _leaves_equal(restore_checkpoint(payload, cfg, PCFG, env),
                             pop)

    def test_compaction_bounds_journal_over_50_generations(self, cfg, pop,
                                                           tmp_path):
        path = str(tmp_path / "pbt.journal")
        journal = SnapshotJournal(path, compact_every=5,
                                  kind=PBT_CHECKPOINT_KIND)
        base = self._payload(pop, cfg)
        for g in range(50):
            journal.write(dict(base, generation=g + 1))
        journal.close()
        records, stats = replay(path)
        # O(one snapshot), never O(uptime): the file holds at most one
        # compacted record + compact_every live appends
        assert stats["replayed"] <= 6
        payload, _stats = load_checkpoint(path)
        assert payload["generation"] == 50


# --------------------------------------------------------------------------
# resume parity: the headline robustness pin
# --------------------------------------------------------------------------

class TestResumeParity:
    def test_service_ticks_bit_equal_one_shot_run(self, env, cfg):
        """A service running one generation per tick IS `train_pbt` —
        the absolute generation counter keeps the exchange key stream
        identical, so state, fitness history and lineage match bitwise."""
        svc = _service(env, cfg)
        rows = [_tick(svc) for _ in range(3)]
        assert [r["generation"] for r in rows] == [0, 1, 2]

        straight = train_pbt(KEY, env, cfg, PCFG._replace(generations=3))
        assert _leaves_equal(svc._pop, straight.state)
        for got, want in zip(svc.history, straight.history):
            assert got["fitness"] == want["fitness"]
            assert got["lineage"] == want["lineage"]
            assert got["hypers"] == want["hypers"]

    def test_kill_after_torn_append_resumes_bit_identical(self, env, cfg,
                                                          tmp_path):
        """Kill the service so its LAST checkpoint append is torn: the
        restart falls back to the previous intact record, re-trains the
        lost generation on the SAME absolute key, and the merged run is
        BIT-identical to one that never died."""
        path = str(tmp_path / "pbt.journal")
        a = _service(env, cfg, checkpoint_path=path, checkpoint_every=1)
        _tick(a)
        _tick(a)
        assert a.generation == 2
        a.close()
        chaos.torn_tail(path, keep_bytes=37)    # the gen-2 append dies

        b = _service(env, cfg, checkpoint_path=path, checkpoint_every=1)
        out = _tick(b)                          # re-trains generation 1
        assert out["bootstrap"] == {"resumed": True, "generation": 1}
        assert b.resumed_at == 1
        _tick(b)                                # generation 2
        assert b.generation == 3

        straight = train_pbt(KEY, env, cfg, PCFG._replace(generations=3))
        assert _leaves_equal(b._pop, straight.state)
        assert len(b.history) == 3
        for got, want in zip(b.history, straight.history):
            assert got["fitness"] == want["fitness"]
            assert got["lineage"] == want["lineage"]
        b.close()

    def test_cli_resume_provenance(self, env, cfg, tmp_path, capsys):
        from ai_crypto_trader_tpu import cli

        path = str(tmp_path / "cli.journal")
        args = ["rl", "--population", "8", "--generations", "1",
                "--iters", "1", "--envs", "2", "--rollout", "2",
                "--scenarios", "2", "--steps", "64", "--episode-len", "32"]
        cli.main(args + ["--checkpoint", path])
        capsys.readouterr()
        cli.main(args + ["--checkpoint", path, "--resume", path])
        out = capsys.readouterr().out
        assert f"resumed@gen=1 from {path}" in out
        # the gen table carries provenance: replayed vs live rows
        assert " ckpt " in out or "ckpt" in out
        assert "live" in out

    def test_cli_resume_refuses_missing_checkpoint(self, tmp_path):
        from ai_crypto_trader_tpu import cli

        with pytest.raises(SystemExit, match="no intact checkpoint"):
            cli.main(["rl", "--population", "8", "--generations", "1",
                      "--iters", "1", "--envs", "2", "--rollout", "2",
                      "--scenarios", "2", "--steps", "64",
                      "--episode-len", "32",
                      "--resume", str(tmp_path / "absent.journal")])


# --------------------------------------------------------------------------
# containment: blast radius == the poisoned member, heal == forced exploit
# --------------------------------------------------------------------------

class TestContainment:
    def _poisoned_run(self, env, cfg, mid, generations=1):
        pop = pop_init(KEY, env, cfg, PCFG)
        pop = chaos.poison_member_state(pop, mid, field="params")
        return train_pbt(KEY, env, cfg,
                         PCFG._replace(generations=generations),
                         init_pop=pop)

    def test_healthy_members_bit_identical_p8(self, env, cfg):
        clean, mid = _mid_member(env, cfg)
        res = self._poisoned_run(env, cfg, mid)
        row = res.history[0]
        assert row["n_tripped"] == 1
        assert row["quarantined"][mid] is True
        mask = np.arange(PCFG.population) != mid
        f_clean = np.array(clean.history[0]["fitness"])
        f_pois = np.array(row["fitness"])
        np.testing.assert_array_equal(f_clean[mask], f_pois[mask])
        # a frozen mid-pack slot leaves both exchange brackets unchanged:
        # every healthy member's POST-exchange state is bit-identical
        for i in np.where(mask)[0]:
            assert _leaves_equal(
                jax.tree.map(lambda x, i=i: x[i], res.state.members),
                jax.tree.map(lambda x, i=i: x[i], clean.state.members))
            assert _leaves_equal(
                jax.tree.map(lambda x, i=i: x[i], res.state.hypers),
                jax.tree.map(lambda x, i=i: x[i], clean.state.hypers))
        # fleet-level stats rank over HEALTHY members only — the NaN
        # fitness never poisons best/mean
        assert row["best_fitness"] == clean.history[0]["best_fitness"]
        assert np.isfinite(row["mean_fitness"])

    @pytest.mark.slow
    def test_healthy_members_bit_identical_p64(self, env, cfg):
        pcfg = PCFG._replace(population=64, generations=1)
        clean = train_pbt(KEY, env, cfg, pcfg)
        # stable rank, matching quantile_split — see _mid_member
        order = np.argsort(np.array(clean.history[0]["fitness"]),
                           kind="stable")
        mid = int(order[32])
        pop = chaos.poison_member_state(pop_init(KEY, env, cfg, pcfg), mid,
                                        field="params")
        res = train_pbt(KEY, env, cfg, pcfg, init_pop=pop)
        assert res.history[0]["n_tripped"] == 1
        mask = np.arange(64) != mid
        np.testing.assert_array_equal(
            np.array(clean.history[0]["fitness"])[mask],
            np.array(res.history[0]["fitness"])[mask])
        for leaf_c, leaf_p in zip(jax.tree.leaves(clean.state.members),
                                  jax.tree.leaves(res.state.members)):
            np.testing.assert_array_equal(np.asarray(leaf_c)[mask],
                                          np.asarray(leaf_p)[mask])

    def test_trip_then_heal_lifecycle(self, env, cfg):
        _clean, mid = _mid_member(env, cfg)
        res = self._poisoned_run(env, cfg, mid, generations=3)
        rows = res.history
        # cooldown=1: trip at gen 0 (frozen exchange), heal at gen 1's
        # exchange — the forced-exploit clone clears the sticky bit
        assert [r["n_tripped"] for r in rows] == [1, 0, 0]
        assert rows[0]["n_quarantined"] == 1
        assert rows[1]["n_healed"] == 1
        assert rows[-1]["n_quarantined"] == 0
        assert np.isfinite(np.array(rows[-1]["fitness"])).all()

    def test_hyper_poison_trips_same_gate(self, env, cfg):
        pop = chaos.poison_member_hypers(pop_init(KEY, env, cfg, PCFG), 3)
        res = train_pbt(KEY, env, cfg, PCFG._replace(generations=1),
                        init_pop=pop)
        assert res.history[0]["n_tripped"] == 1
        assert res.history[0]["quarantined"][3] is True

    def test_heal_is_a_forced_exploit_clone(self, env, cfg):
        """The heal IS PBT's own repair path: an exchange healing slot m
        is BIT-identical to a plain exchange where m simply ranked -inf
        into the exploit bracket — same donor, same fold_in key fork,
        same perturbed hypers."""
        ex = _exchange_program(cfg, _program_pcfg(PCFG))
        pop = pop_init(KEY, env, cfg, PCFG)
        fitness = jnp.arange(8.0)
        key = jax.random.PRNGKey(3)
        m = 4                                   # mid-pack: in no bracket

        def fresh():
            return (jax.tree.map(jnp.array, pop.members),
                    jax.tree.map(jnp.array, pop.hypers))

        zeros_b = jnp.zeros((8,), jnp.bool_)
        zeros_i = jnp.zeros((8,), jnp.int32)
        mem_a, hy_a, q_a, _cd, lin_a = ex(
            *fresh(), zeros_b.at[m].set(True), zeros_i, fitness, key)
        mem_b, hy_b, _qb, _cdb, lin_b = ex(
            *fresh(), zeros_b, zeros_i, fitness.at[m].set(-jnp.inf), key)

        np.testing.assert_array_equal(np.asarray(lin_a), np.asarray(lin_b))
        assert int(lin_a[m]) != m               # healed == cloned
        assert not bool(q_a[m])                 # sticky bit cleared
        assert _leaves_equal(mem_a, mem_b)
        assert _leaves_equal(hy_a, hy_b)
        donor = int(lin_a[m])
        assert _leaves_equal(
            jax.tree.map(lambda x: x[m], mem_a.params),
            jax.tree.map(lambda x: x[donor], pop.members.params))
        # …with the donor's stream forked, never shared
        assert not np.array_equal(np.asarray(mem_a.key[m]),
                                  np.asarray(pop.members.key[donor]))

    def test_frozen_member_invisible_to_healthy_exchange(self, env, cfg):
        """While the cooldown runs, the quarantined slot is neither donor
        nor clone — healthy members see exactly the exchange they would
        have seen had the slot been mid-pack."""
        ex = _exchange_program(cfg, _program_pcfg(PCFG))
        pop = pop_init(KEY, env, cfg, PCFG)
        fitness = jnp.arange(8.0)
        key = jax.random.PRNGKey(3)
        m = 4

        def fresh():
            return (jax.tree.map(jnp.array, pop.members),
                    jax.tree.map(jnp.array, pop.hypers))

        zeros_b = jnp.zeros((8,), jnp.bool_)
        zeros_i = jnp.zeros((8,), jnp.int32)
        mem_f, hy_f, q_f, cd_f, lin_f = ex(
            *fresh(), zeros_b.at[m].set(True), zeros_i.at[m].set(1),
            fitness, key)
        mem_c, hy_c, _q, _cd, lin_c = ex(
            *fresh(), zeros_b, zeros_i, fitness, key)

        np.testing.assert_array_equal(np.asarray(lin_f), np.asarray(lin_c))
        assert int(lin_f[m]) == m               # frozen: passes through
        assert bool(q_f[m]) and int(cd_f[m]) == 0   # cooldown ticked down
        mask = np.arange(8) != m
        for a, b in zip(jax.tree.leaves(mem_f), jax.tree.leaves(mem_c)):
            np.testing.assert_array_equal(np.asarray(a)[mask],
                                          np.asarray(b)[mask])
        for a, b in zip(jax.tree.leaves(hy_f), jax.tree.leaves(hy_c)):
            np.testing.assert_array_equal(np.asarray(a)[mask],
                                          np.asarray(b)[mask])

    def test_trip_and_heal_never_recompile(self, env, cfg):
        """The meshprof sentinel watches the same `pbt_generation` window
        the SteadyStateRecompile alert pages on: a clean run, then a
        poisoned run with a trip AND a heal, share every executable."""
        pcfg = PCFG._replace(generations=2)
        train_pbt(KEY, env, cfg, pcfg)          # warm the program caches
        mp = meshprof.MeshProf()
        with meshprof.use(mp):
            pop = chaos.poison_member_state(pop_init(KEY, env, cfg, PCFG),
                                            2, field="params")
            res = train_pbt(KEY, env, cfg, pcfg, init_pop=pop)
            assert mp.recompiles.steady_total() == 0, \
                mp.recompiles.status()
        assert res.history[0]["n_tripped"] == 1
        assert res.history[1]["n_healed"] == 1


# --------------------------------------------------------------------------
# the service rim: cadence, recalibration, observability, supervision
# --------------------------------------------------------------------------

class TestTrainerService:
    def test_cadence_and_max_generations(self, env, cfg):
        clock = {"t": 1000.0}
        svc = _service(env, cfg, interval_s=60.0, max_generations=2,
                       now_fn=lambda: clock["t"])
        assert _tick(svc)["ran"] is True
        assert _tick(svc)["ran"] is False       # interval gate holds
        clock["t"] += 60.0
        assert _tick(svc)["ran"] is True
        clock["t"] += 60.0
        out = _tick(svc)
        assert out == {"ran": False, "reason": "complete"}
        assert svc.generation == 2

    def test_recalibration_good_then_poisoned_keeps_last_good(self, env,
                                                              cfg):
        feed = {"recs": _good_records()}
        m = MetricsRegistry()
        svc = _service(env, cfg, depth_source=lambda: feed["recs"],
                       recalibrate_every=2, metrics=m)
        for _ in range(3):                      # recalibrates at gen 2
            _tick(svc)
        assert svc.last_recalibration["ok"] is True
        assert svc.recalibration_failures == 0
        good_flow, good_env = svc.flow, svc.env_params
        assert good_flow is not None

        feed["recs"] = chaos.poisoned_depth_records(mode="nan_spread")
        _tick(svc)                              # gen 3: no recalibration
        out = _tick(svc)                        # gen 4: poisoned window
        r = out["recalibration"]
        assert r["ok"] is False
        assert "CalibrationPoisoned" in r["reason"]
        assert svc.recalibration_failures == 1
        # last-good fallback: the fleet keeps training on the good fit
        assert svc.flow is good_flow
        assert svc.env_params is good_env
        failures = [v for k, v in m.counters.items()
                    if "pbt_recalibration_failures_total" in str(k)]
        assert sum(failures) == 1.0

    def test_every_poison_mode_is_refused(self):
        from ai_crypto_trader_tpu.sim.calibrate import (
            CalibrationPoisoned,
            validate_depth_records,
        )

        for mode in ("nan_spread", "zero_depth", "crossed"):
            with pytest.raises(CalibrationPoisoned):
                validate_depth_records(
                    chaos.poisoned_depth_records(mode=mode))

    def test_recalibration_swap_is_a_transfer_never_a_recompile(self, env,
                                                                cfg):
        """EnvParams are array content: after a successful re-fit the
        next generation reuses every executable (the meshprof sentinel
        would flag a shape-changing swap as a steady-state recompile)."""
        feed = {"recs": _good_records()}
        svc = _service(env, cfg, depth_source=lambda: feed["recs"],
                       recalibrate_every=2)
        _tick(svc)
        _tick(svc)
        mp = meshprof.MeshProf()
        with meshprof.use(mp):
            out = _tick(svc)                    # gen 2: recalibrate + train
            assert out["recalibration"]["ok"] is True
            assert svc.env_params.close.shape == env.close.shape
            assert mp.recompiles.steady_total() == 0, \
                mp.recompiles.status()

    def test_gauges_alert_inputs_and_status(self, env, cfg):
        clock = {"t": 1000.0}
        m = MetricsRegistry()
        svc = _service(env, cfg, metrics=m, interval_s=30.0,
                       now_fn=lambda: clock["t"], checkpoint_every=1)
        # a poisoned member at init: the first tick trips quarantine and
        # the MemberQuarantined rule fires off the service's own inputs
        svc.env_params = env
        svc._pop = chaos.poison_member_state(
            pop_init(KEY, env, cfg, PCFG), 5, field="params")
        _tick(svc)
        gauges = {str(k): v for k, v in m.gauges.items()}
        assert any("pbt_generation" in k for k in gauges)
        assert any("pbt_quarantined_members" in k for k in gauges)
        assert any("pbt_last_generation_timestamp" in k for k in gauges)

        from ai_crypto_trader_tpu.utils.alerts import default_rules

        rules = {r.name: r for r in default_rules()}
        state = svc.alert_state()
        assert state["pbt_quarantined_members"] == 1
        assert rules["MemberQuarantined"].predicate(state)
        assert not rules["TrainingFleetStalled"].predicate(state)
        clock["t"] += svc._stall_threshold() + 1.0
        assert rules["TrainingFleetStalled"].predicate(svc.alert_state())

        status = svc.status()
        assert status["generation"] == 1
        assert status["population"] == 8
        assert status["quarantined_members"] == 1
        assert status["quarantine_trips"] == 1

    def test_attach_trainer_runs_under_stage_supervision(self, env, cfg):
        sys.path.insert(0, os.path.dirname(__file__))
        from test_shell import _series

        from ai_crypto_trader_tpu.shell.dashboard_server import (
            DashboardServer,
        )
        from ai_crypto_trader_tpu.shell.exchange import FakeExchange
        from ai_crypto_trader_tpu.shell.launcher import TradingSystem

        ex = FakeExchange({"BTCUSDC": _series()})
        system = TradingSystem(ex, ["BTCUSDC"], now_fn=lambda: 0.0)
        svc = _service(env, cfg, max_generations=1)
        system.attach_trainer(svc)
        assert "trainer" in system.stage_breakers
        assert svc.metrics is system.metrics    # gauges land in /metrics
        asyncio.run(system._run_extra_services())
        assert svc.generation == 1
        # success beat the stage breaker, not the plain-isolation path
        assert system.stage_breakers["trainer"].failures == 0
        state = system._alert_state()
        assert state["pbt_quarantined_members"] == 0
        assert "pbt_stall_after_s" in state
        # the dashboard's /state.json carries the training block
        block = DashboardServer(system, port=0).state()["training"]
        assert block["generation"] == 1
        assert block["population"] == 8

    def test_crash_looping_trainer_is_quarantined_not_fatal(self, env,
                                                            cfg):
        sys.path.insert(0, os.path.dirname(__file__))
        from test_shell import _series

        from ai_crypto_trader_tpu.shell.exchange import FakeExchange
        from ai_crypto_trader_tpu.shell.launcher import TradingSystem

        ex = FakeExchange({"BTCUSDC": _series()})
        clock = {"t": 0.0}
        system = TradingSystem(ex, ["BTCUSDC"], now_fn=lambda: clock["t"])

        class Exploder:
            name = "trainer"

            async def run_once(self):
                raise RuntimeError("boom")

        system.attach_trainer(Exploder())
        br = system.stage_breakers["trainer"]
        for _ in range(system.stage_max_failures + 1):
            asyncio.run(system._run_extra_services())
            clock["t"] += 1e6                   # clear the backoff window
        assert br.failures >= system.stage_max_failures
        assert br.quarantined is True           # crash loop contained


# --------------------------------------------------------------------------
# the chaos soak: kills + poison + bad capture window in one lifetime
# --------------------------------------------------------------------------

def _run_soak(env, cfg, tmp_path, kills):
    """Shared soak driver: a checkpointing/recalibrating/adopting service
    lifetime with `kills` process deaths (the last one torn mid-append),
    one poisoned member and one poisoned recalibration window.  Returns
    the final service and its journal path."""
    from ai_crypto_trader_tpu.obs.scorecard import Scorecard
    from ai_crypto_trader_tpu.strategy.registry import ModelRegistry

    path = str(tmp_path / "soak.journal")
    feed = {"recs": _good_records()}
    registry = ModelRegistry(path=str(tmp_path / "registry.json"))
    scorecard = Scorecard()
    metrics = MetricsRegistry()

    def spawn():
        return _service(env, cfg, checkpoint_path=path, checkpoint_every=1,
                        depth_source=lambda: feed["recs"],
                        recalibrate_every=2, registry=registry,
                        scorecard=scorecard, metrics=metrics)

    svc = spawn()
    _tick(svc)                      # gen 0 (winner adopted) -> ckpt@1
    # poison one member mid-lifetime: gen 1 trips (the sticky bit rides
    # ckpt@2, so even a torn-tail resume replays the quarantine)
    svc._pop = chaos.poison_member_state(svc._pop, 5, field="params")
    _tick(svc)                      # gen 1: trip, frozen   -> ckpt@2
    _tick(svc)                      # gen 2: good recal + heal -> ckpt@3
    for k in range(kills):
        svc.close()                 # process death…
        if k == kills - 1:
            # …this one mid-append: tear the newest checkpoint record
            chaos.torn_tail(path, keep_bytes=43)
        svc = spawn()
        if k < kills - 1:
            _tick(svc)              # a generation between kills
    # a poisoned capture window in the resumed lifetime: tick until a
    # recalibration generation refuses it (recalibrate_every=2 -> <=2)
    feed["recs"] = chaos.poisoned_depth_records(mode="zero_depth")
    for _ in range(3):
        _tick(svc)
        if svc.recalibration_failures:
            break
    feed["recs"] = _good_records(seed=1)
    while svc.generation % 2:       # land on the next recal generation
        _tick(svc)
    _tick(svc)                      # …which re-fits cleanly
    return svc, path


class TestChaosSoak:
    def test_soak_smoke_ends_healthy_with_adopted_winner(self, env, cfg,
                                                         tmp_path):
        svc, path = _run_soak(env, cfg, tmp_path, kills=1)
        assert svc.resumed_at is not None       # the kill really resumed
        last = svc.history[-1]
        assert last["n_quarantined"] == 0       # the poisoned member healed
        assert np.isfinite(np.array(last["fitness"])).all()
        # the trip and the heal survive the kill in the restored lineage
        assert any(r["n_tripped"] == 1 for r in svc.history)
        assert any(r["n_healed"] == 1 for r in svc.history)
        assert svc.recalibration_failures == 1  # one poisoned window, counted
        assert svc.last_recalibration["ok"] is True     # …and recovered
        # >= 1 winner went through the adoption gate, verdict journaled
        assert len(svc.adoptions) >= 1
        assert all("adopted" in v for v in svc.adoptions)
        svc.close()
        records, _stats = replay(path)
        kinds = {r["kind"] for r in records}
        assert "pbt_adoption" in kinds

    @pytest.mark.slow
    def test_soak_long_blast_radius_and_zero_recompiles(self, env, cfg,
                                                        tmp_path):
        """The full ISSUE-20 soak: 2 kills (one torn mid-append), one
        poisoned member, one poisoned recalibration window — ends
        healthy, blast radius == the faulted member (healthy fitness
        rows bit-identical to a clean twin until the heal reshuffles the
        exploit bracket), zero steady-state recompiles end to end."""
        clean = train_pbt(KEY, env, cfg, PCFG._replace(generations=2))
        mp = meshprof.MeshProf()
        with meshprof.use(mp):
            svc, _path = _run_soak(env, cfg, tmp_path, kills=2)
            assert mp.recompiles.steady_total() == 0, \
                mp.recompiles.status()
        assert svc.history[-1]["n_quarantined"] == 0
        assert np.isfinite(np.array(svc.history[-1]["fitness"])).all()
        assert svc.recalibration_failures == 1
        assert len(svc.adoptions) >= 1
        # blast radius: at the trip generation every healthy member's
        # fitness is bit-identical to the clean twin's
        trip_row = next(r for r in svc.history if r["n_tripped"] == 1)
        g = trip_row["generation"]
        mask = ~np.asarray(trip_row["quarantined"])
        np.testing.assert_array_equal(
            np.array(clean.history[g]["fitness"])[mask],
            np.array(trip_row["fitness"])[mask])
        svc.close()
